"""Weight initializers (reference: python/mxnet/initializer.py).

Registry + the full set: Zero/One/Constant/Uniform/Normal/Orthogonal/
Xavier/MSRAPrelu/Bilinear/LSTMBias/FusedRNN.  Initializers fill NDArrays
in place (reference semantics) using the framework PRNG chain.
"""

from __future__ import annotations

import json
import re

import numpy as _np

from .base import Registry

_REG = Registry("initializer")


class InitDesc(str):
    """Name+attrs descriptor passed to initializers
    (reference: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; callable on (InitDesc, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        if getattr(desc, "global_init", None) is None and isinstance(desc, InitDesc):
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        elif name.endswith("quantize"):
            # offline-quantized params: values are always loaded, never
            # trained from init (contrib/quantization.py _quantize_params)
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # individual fillers -------------------------------------------------
    def _fill(self, arr, value):
        arr[:] = value

    def _init_zero(self, _, arr):
        self._fill(arr, 0.0)

    def _init_one(self, _, arr):
        self._fill(arr, 1.0)

    def _init_bias(self, _, arr):
        self._fill(arr, 0.0)

    def _init_gamma(self, _, arr):
        self._fill(arr, 1.0)

    def _init_beta(self, _, arr):
        self._fill(arr, 0.0)

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s; name a known suffix "
            "(weight/bias/gamma/beta/...) or set an explicit init" % name
        )


def register(klass):
    _REG.register(klass)
    return klass


_ALIASES = {"zeros": "zero", "ones": "one"}


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name.startswith("["):  # dumps() round-trip
        cls_name, kw = json.loads(name)
        return _REG.create(cls_name, **kw)
    return _REG.create(_ALIASES.get(name.lower(), name), **kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._fill(arr, 0.0)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._fill(arr, 1.0)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._fill(arr, self.value)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        from .ndarray import random as ndr

        arr[:] = ndr.uniform(-self.scale, self.scale, shape=arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        from .ndarray import random as ndr

        arr[:] = ndr.normal(0.0, self.sigma, shape=arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _s, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(_np.float32)


@register
class Xavier(Initializer):
    """reference: initializer.py Xavier (magnitude/factor_type/rnd_type)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        from .ndarray import random as ndr

        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires ndim >= 2 (got %s for %s)" % (shape, name))
        if len(shape) > 2:
            hw_scale = float(_np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[
            self.factor_type]
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = ndr.uniform(-scale, scale, shape=shape)
        else:
            arr[:] = ndr.normal(0, scale, shape=shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for UpSampling deconv weights)."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        import numpy as _np

        num_hidden = arr.shape[0] // 4
        a = _np.zeros(arr.shape, dtype=_np.float32)
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = a


@register
class FusedRNN(Initializer):
    """Initialize a FusedRNNCell's packed parameter vector by unpacking
    it, initializing each per-gate piece (forget-gate biases get
    ``forget_bias``), and packing back (reference: initializer.py
    FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            init = create(init)
        super().__init__(
            init=init.dumps() if init is not None else None,
            num_hidden=num_hidden, num_layers=num_layers, mode=mode,
            bidirectional=bidirectional, forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn import rnn_cell

        cell = rnn_cell.FusedRNNCell(
            self._num_hidden, self._num_layers, self._mode,
            self._bidirectional, forget_bias=self._forget_bias, prefix="")
        pieces = cell.unpack_weights({"parameters": arr})
        for name, piece in pieces.items():
            if self._mode == "lstm" and name.endswith("_f_bias"):
                piece[:] = self._forget_bias
                continue
            sub_init = self._init
            if sub_init is None:
                sub_init = getattr(desc, "global_init", None) or Uniform(0.1)
            sub_desc = InitDesc(name)
            sub_desc.global_init = getattr(desc, "global_init", None)
            sub_init(sub_desc, piece)
        arr[:] = cell.pack_weights(pieces)["parameters"]


class Mixed:
    """Pattern-matched initializer mix (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter %s did not match any pattern" % name)


class Load:
    """Init from saved dict, falling back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k.replace("arg:", "").replace("aux:", ""): v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise ValueError("shape mismatch loading %s" % name)
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise ValueError("no init for %s" % name)
            self.default_init(name, arr)


# module-level alias namespace used as ``mx.init``
class _InitNamespace:
    Initializer = Initializer
    InitDesc = InitDesc
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    Mixed = Mixed
    Load = Load
    create = staticmethod(create)


init = _InitNamespace
