"""Hang forensics — all-thread stack dumps on demand.

The flight recorder (health.py) covers crashes and NaN storms, but a
HUNG training process — a deadlocked collective, a stuck host
callback, an input pipeline that never returns — leaves nothing.  This
module wires Python's ``faulthandler`` so an operator can ask a live
(even wedged) process for every thread's stack:

* ``MXNET_TPU_STACKDUMP=<file>`` arms SIGUSR2 at import (the same
  activation chain as ``MXNET_TPU_DIAG``'s SIGUSR1): ``kill -USR2
  <pid>`` writes the dump and training continues.
* :func:`dump_stacks` does the same programmatically (watchdogs,
  tests).

The dump is written through ``checkpoint.atomic_write`` — the one
atomic-write primitive every persistence path routes through — so a
reader never sees a torn file, and the path is rank-suffixed by
``log.rank_suffix_path`` so multi-process launches don't clobber each
other.  A header maps thread idents to Python thread names (the
``faulthandler`` traceback identifies threads by ident only).
Docs: docs/OBSERVABILITY.md "Hang forensics".
"""

from __future__ import annotations

import faulthandler
import os
import threading

__all__ = ["dump_stacks", "install", "installed"]

DEFAULT_PATH = "mxnet_tpu_stacks.txt"

_state = {"installed": False, "path": None}


def installed():
    """True once the SIGUSR2 handler is armed."""
    return _state["installed"]


def dump_stacks(path=None):
    """Write every thread's current Python stack to ``path`` (default:
    the armed/env path, else ``mxnet_tpu_stacks.txt``) atomically,
    rank-suffixed.  Returns the absolute path written."""
    from .checkpoint import atomic_write
    from .log import process_identity, rank_suffix_path

    path = path or _state["path"] \
        or os.environ.get("MXNET_TPU_STACKDUMP") or DEFAULT_PATH
    path = rank_suffix_path(path)
    ident = process_identity()
    names = {t.ident: t.name for t in threading.enumerate()}
    with atomic_write(path) as tmp:
        with open(tmp, "w") as f:
            f.write("mxnet_tpu stack dump: pid=%d identity=%s\n"
                    % (os.getpid(),
                       "%s%d/%d" % (ident["role"], ident["rank"],
                                    ident["num_workers"])
                       if ident else "single-process"))
            f.write("threads: %s\n\n"
                    % ", ".join("0x%x=%s" % (i, n)
                                for i, n in sorted(names.items())
                                if i is not None))
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)
    from . import runtime_stats as _rts

    _rts.inc("stack_dumps")
    return os.path.abspath(path)


def install(path=None):
    """Arm SIGUSR2 -> :func:`dump_stacks`.  Tolerates platforms
    without SIGUSR2 and non-main threads (returns False), like the
    SIGUSR1 diag handler."""
    import signal

    sig = getattr(signal, "SIGUSR2", None)
    if sig is None:
        return False

    def _handler(_signum, _frame):
        try:
            dump_stacks()
        except Exception:  # a forensics request must never kill training
            from .log import get_logger

            get_logger("stackdump").exception(
                "MXNET_TPU_STACKDUMP dump failed")

    try:
        signal.signal(sig, _handler)
    except ValueError:  # not the main thread
        return False
    if path:
        _state["path"] = path
    _state["installed"] = True
    return True


def _activate_from_env():
    """``MXNET_TPU_STACKDUMP=<file>``: arm the SIGUSR2 handler — called
    from runtime_stats' import-time activation chain."""
    path = os.environ.get("MXNET_TPU_STACKDUMP")
    if not path:
        return False
    return install(path)
