"""Numerics health layer — device-resident NaN/Inf sentinels, an async
tensor-stat monitor, and the training flight recorder.

PR 2 made *time* observable and PR 3 made *memory/FLOPs* observable;
this module covers the axis that actually kills training runs:
numerical health.  The legacy ``monitor.Monitor`` computed statistics
on host numpy, blocking mid-forward on every watched tensor — the
exact host-sync anti-pattern mxlint guards against.  Here statistics
are computed where the data lives:

- :func:`stat_kernel` builds one jitted per-tensor kernel (selectable
  stat set: nan count, inf count, abs-mean, min/max, l2-norm,
  zero-fraction) returning a tiny device vector.  Next to the ops it
  watches the fused XLA reductions cost near nothing ("Operator Fusion
  in XLA", arXiv:2301.13062) — the same keep-it-on-device discipline
  that motivates full-program TPU compilation (arXiv:1810.09868).
- :class:`HealthMonitor` queues those device vectors **without
  blocking**; host materialization happens only at rate-limited drain
  points (end-of-interval, :meth:`HealthMonitor.report`, a dump) —
  one deliberate sync sink (:func:`_fetch`), pragma'd once per the
  callgraph rule.  Feeding surfaces: Gluon forward hooks
  (:meth:`HealthMonitor.install`), ``gluon.Trainer`` gradient hooks
  (global grad-norm + per-param update-to-weight ratio), and the
  symbolic executor's fwd/bwd outputs.
- :class:`FlightRecorder` keeps a bounded ring of recent per-step
  health records (step, loss, grad-norm, nan/inf flags, recompile and
  memory counters snapshotted from ``runtime_stats``) and dumps it
  atomically on first-NaN detection, on an unhandled exception inside
  ``Trainer.step``, and with the ``MXNET_TPU_DIAG`` SIGUSR1 snapshot
  (``runtime_stats.diag_snapshot`` embeds the health section).

Cost model (the PR 2 contract, pinned by ``tests/test_bench_gate.py``):
disabled (the default), every hook site pays one dict read and nothing
else — no kernel, no queue entry, no allocation.  Enabled, an observed
tensor costs one cached-jit kernel dispatch plus a deque append; the
host pays only at drain.

Environment variables
---------------------
``MXNET_TPU_HEALTH=1``              enable the global monitor at import.
``MXNET_TPU_HEALTH_INTERVAL``       sample/drain every N steps (default 1).
``MXNET_TPU_HEALTH_STATS``          comma list from :data:`STAT_NAMES`
    (default ``nan_count,inf_count,abs_mean,l2_norm``; the two
    sentinel counts are always included).
``MXNET_TPU_HEALTH_RING``           flight-recorder capacity (default 256).
``MXNET_TPU_HEALTH_DUMP``           flight-recorder dump path (default
    ``mxnet_tpu_flight.json``; with ``MXNET_TPU_DIAG`` set the full
    diag dump is written instead, health section included).
``MXNET_TPU_HEALTH_WARN_INTERVAL``  min seconds between NaN warnings
    (default 60).

Docs: docs/OBSERVABILITY.md "Numerics health".
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import re
import time

from . import profiler as _profiler
from . import runtime_stats as _rts
from . import stepstats as _stepstats
from .log import (get_logger, rank_suffix_path, warn_once,
                  warn_rate_limited)

__all__ = ["STAT_NAMES", "DEFAULT_STATS", "stat_kernel", "tensor_stats",
           "global_norm", "update_ratio", "HealthMonitor",
           "FlightRecorder", "enable", "disable", "is_enabled", "monitor",
           "observe", "snapshot", "dump_flight", "reset",
           "HEALTH_INTERVAL", "WARN_INTERVAL", "RING_CAPACITY"]

HEALTH_INTERVAL = int(os.environ.get("MXNET_TPU_HEALTH_INTERVAL", "1"))
WARN_INTERVAL = float(os.environ.get("MXNET_TPU_HEALTH_WARN_INTERVAL", "60"))
RING_CAPACITY = int(os.environ.get("MXNET_TPU_HEALTH_RING", "256"))

# pending device stat entries kept before a drain; a runaway producer
# (observe without end_step) drops the oldest and counts the drop
_PENDING_CAP = int(os.environ.get("MXNET_TPU_HEALTH_QUEUE", "4096"))

STAT_NAMES = ("nan_count", "inf_count", "abs_mean", "min", "max",
              "l2_norm", "zero_frac")
DEFAULT_STATS = ("nan_count", "inf_count", "abs_mean", "l2_norm")


def _env_stats():
    """The ``MXNET_TPU_HEALTH_STATS`` selection, or None when unset —
    read per-monitor (like ``HEALTH_INTERVAL``) so programmatic
    ``enable()`` without an explicit ``stats`` honors the env too."""
    raw = os.environ.get("MXNET_TPU_HEALTH_STATS")
    if not raw:
        return None
    return tuple(s.strip() for s in raw.split(",") if s.strip())

# the flight recorder's nan/inf flags need the sentinel counts, so a
# custom stat selection always includes them
SENTINEL_STATS = ("nan_count", "inf_count")

_state = {"on": False}
_GLOBAL: list = []          # [HealthMonitor] while enabled

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.health"))
    return _logger_cache[0]


# ------------------------------------------------------------- kernels


_STAT_IMPLS = None
_KERNELS: dict = {}
_NORM_KERNEL: list = []
_RATIO_KERNEL: list = []
_tracer_cls: list = []      # cached jax.core.Tracer


def _stat_impls():
    global _STAT_IMPLS
    if _STAT_IMPLS is None:
        import jax.numpy as jnp

        f32 = jnp.float32
        _STAT_IMPLS = {
            # all stats computed in float32: NaN/Inf survive the cast,
            # integer inputs map to clean zero sentinel counts
            "nan_count": lambda x, xf: jnp.isnan(xf).sum().astype(f32),
            "inf_count": lambda x, xf: jnp.isinf(xf).sum().astype(f32),
            "abs_mean": lambda x, xf: jnp.abs(xf).mean(),
            "min": lambda x, xf: xf.min(),
            "max": lambda x, xf: xf.max(),
            "l2_norm": lambda x, xf: jnp.sqrt((xf * xf).sum()),
            "zero_frac": lambda x, xf: (x == 0).mean(dtype=f32),
        }
    return _STAT_IMPLS


def stat_kernel(stats=DEFAULT_STATS):
    """The jitted per-tensor stat kernel for a stat selection: maps one
    array to a ``float32[len(stats)]`` **device** vector (one fused XLA
    reduction; jit-cached per stat set and input aval).  The returned
    callable is pure and host-sync-free — materialize its result only
    at a drain point."""
    stats = tuple(stats)
    kern = _KERNELS.get(stats)
    if kern is not None:
        return kern
    unknown = sorted(set(stats) - set(STAT_NAMES))
    if unknown:
        raise ValueError("unknown health stat(s) %s (known: %s)"
                         % (", ".join(unknown), ", ".join(STAT_NAMES)))
    import jax
    import jax.numpy as jnp

    impls = _stat_impls()
    chosen = [impls[s] for s in stats]

    def _stats(x):
        xf = x.astype(jnp.float32)
        return jnp.stack([f(x, xf) for f in chosen])

    kern = _KERNELS[stats] = jax.jit(_stats)
    return kern


def tensor_stats(value, stats=DEFAULT_STATS):
    """Stats of one NDArray / jax array as a host dict — convenience
    wrapper (kernel + immediate fetch), NOT for compute paths."""
    data = getattr(value, "_data", value)
    vec = _fetch([stat_kernel(stats)(data)])[0]
    return dict(zip(stats, (float(v) for v in vec)))


def global_norm(values):
    """Fused global L2 norm of a list of jax arrays, on device: one
    jitted ``sqrt(sum_i sum(x_i^2))`` over the whole list (jit-cached
    per shape set — parameters are fixed across steps, so steady state
    is one executable).  Returns a device scalar; also the kernel
    behind ``gluon.utils.clip_global_norm``'s fused finite check."""
    if not _NORM_KERNEL:
        import jax
        import jax.numpy as jnp

        def _norm(vals):
            total = None
            for v in vals:
                s = (v.astype(jnp.float32) ** 2).sum()
                total = s if total is None else total + s
            return jnp.sqrt(total)

        _NORM_KERNEL.append(jax.jit(_norm))
    return _NORM_KERNEL[0](list(values))


def update_ratio(new, old):
    """Per-parameter update-to-weight ratio ``||new-old|| / ||old||``
    as a device scalar (one fused kernel; eps-guarded denominator)."""
    if not _RATIO_KERNEL:
        import jax
        import jax.numpy as jnp

        def _ratio(n, o):
            nf = n.astype(jnp.float32)
            of = o.astype(jnp.float32)
            un = jnp.sqrt(((nf - of) ** 2).sum())
            wn = jnp.sqrt((of * of).sum())
            return un / (wn + 1e-12)

        _RATIO_KERNEL.append(jax.jit(_ratio))
    return _RATIO_KERNEL[0](new, old)


def _concrete(buf):
    """True for a real device array (not a tracer, not a host value) —
    tracers must never be queued across trace boundaries."""
    import jax

    if not _tracer_cls:
        _tracer_cls.append(jax.core.Tracer)
    return isinstance(buf, jax.Array) and not isinstance(buf,
                                                        _tracer_cls[0])


def _fetch(values):
    """Materialize queued device stat buffers on host.

    THE deliberate host-sync sink of the health layer: every queued
    vector is tiny (a handful of float32s), the whole list transfers
    in ONE batched device_get, and this runs only at rate-limited
    drain points, never on a compute path."""
    import jax

    return jax.device_get(list(values))  # mxlint: disable=trace-host-sync


# ------------------------------------------------------ flight recorder


_flight_seq = itertools.count()


class FlightRecorder:
    """Bounded ring of recent per-step health records, dumped atomically
    (write-temp + ``os.replace``) when training goes numerically bad."""

    def __init__(self, capacity=None):
        self._ring = collections.deque(maxlen=capacity or RING_CAPACITY)
        self.dumps = 0
        self.last_dump_path = None

    def append(self, record):
        self._ring.append(record)

    def records(self):
        return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def dump(self, path=None, reason=None, health=None):
        """Atomically write the ring (plus the owning monitor's summary)
        as JSON; returns the absolute path.  Unique temp name per call,
        same torn-file discipline as ``runtime_stats.dump_diag``."""
        # explicit paths are honored verbatim; the env/default fallback
        # self-suffixes with role+rank so multi-rank runs without
        # launch.py cannot clobber rank 0's flight dump
        path = path or rank_suffix_path(
            os.environ.get("MXNET_TPU_HEALTH_DUMP")
            or "mxnet_tpu_flight.json")
        path = os.path.abspath(path)
        payload = {"version": 1, "pid": os.getpid(), "time": time.time(),
                   "reason": reason,
                   "health": health if health is not None
                   else {"flight": self.records()}}
        tmp = os.path.join(os.path.dirname(path),
                           ".%s.%d.%d.tmp" % (os.path.basename(path),
                                              os.getpid(),
                                              next(_flight_seq)))
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
        os.replace(tmp, path)
        self.dumps += 1
        self.last_dump_path = path
        return path


# ------------------------------------------------------- health monitor


class HealthMonitor:
    """Asynchronous device-resident numerics monitor.

    Producers call :meth:`observe` (or the install'd Gluon hooks /
    Trainer feeds); every observation enqueues a tiny device vector and
    returns immediately.  One call to :meth:`end_step` per training
    step advances the clock; at each sampled interval boundary the
    pending queue is drained to host in one batch, the flight recorder
    gets a per-step record, chrome-trace counters (``grad_norm``,
    ``nan_total``) are emitted while the profiler runs, and the first
    NaN/Inf fires a rate-limited warning naming the earliest offending
    tensor plus an atomic flight dump.
    """

    def __init__(self, interval=None, stats=None, pattern=".*",
                 ring=None, dump_path=None, warn_interval=None):
        self.interval = max(1, int(interval or HEALTH_INTERVAL))
        stats = tuple(stats or _env_stats() or DEFAULT_STATS)
        self.stats = stats + tuple(s for s in SENTINEL_STATS
                                   if s not in stats)
        self.re_pattern = re.compile(pattern)
        self.dump_path = dump_path
        self.warn_interval = WARN_INTERVAL if warn_interval is None \
            else warn_interval
        self._kernel = stat_kernel(self.stats)
        # deactivated by disable()/enable()-replacement so orphaned
        # install() hooks stop dispatching kernels into a dead queue
        self.active = True
        # pending device values, FIFO: ("stats", step, key, vec) |
        # ("scalar", step, key, scalar) — drained in arrival order
        self._pending: collections.deque = collections.deque()
        self.step = 0
        self._sampling = True          # step 0 is a sample step
        self.flight = FlightRecorder(ring)
        self.records: collections.deque = collections.deque(
            maxlen=self.flight._ring.maxlen)
        self.totals = {"observed": 0, "drained": 0, "dropped": 0,
                       "nan_steps": 0, "inf_steps": 0}
        self.first_nan = None          # {"step", "key", ...} once seen
        self._nan_dumped = False
        self._installed: list = []

    # ------------------------------------------------------- producers
    @property
    def sampling(self):
        """True while the current step is a sampled one — producers may
        use this to skip building feed lists entirely."""
        return self._sampling

    def _enqueue(self, entry):
        if len(self._pending) >= _PENDING_CAP:
            self._pending.popleft()
            self.totals["dropped"] += 1
        self._pending.append(entry)
        self.totals["observed"] += 1
        _rts.inc("health_observed")

    def observe(self, key, value):
        """Queue the stat vector of one tensor under ``key`` — a cached
        jitted kernel dispatch plus a deque append, no host sync.
        Tracer-backed values (inside a staged/hybridized trace) and
        non-matching keys are skipped."""
        if not (self.active and self._sampling) \
                or not self.re_pattern.match(key):
            return
        data = getattr(value, "_data", value)
        if not _concrete(data):
            return
        self._enqueue(("stats", self.step, key, self._kernel(data)))

    def observe_scalar(self, key, device_scalar):
        """Queue an already-computed device scalar (grad-norm,
        update-to-weight ratio, loss) under ``key``."""
        if not (self.active and self._sampling):
            return
        if not _concrete(device_scalar):
            return
        self._enqueue(("scalar", self.step, key, device_scalar))

    def observe_grads(self, named_grads):
        """Trainer gradient hook: one fused global grad-norm over all
        gradients (queued as ``grad_norm``) plus per-gradient sentinel
        stats for pattern-matched names (``grad:<param>``)."""
        if not (self.active and self._sampling) or not named_grads:
            return
        vals = [getattr(g, "_data", g) for _, g in named_grads]
        if not all(_concrete(v) for v in vals):
            return
        self._enqueue(("scalar", self.step, "grad_norm",
                       global_norm(vals)))
        for (name, _), v in zip(named_grads, vals):
            key = "grad:%s" % name
            if self.re_pattern.match(key):
                self._enqueue(("stats", self.step, key, self._kernel(v)))

    def observe_update(self, name, new, old):
        """Trainer update hook: per-parameter update-to-weight ratio
        (``uwr:<param>``) from the pre/post-update device buffers;
        pattern-scoped like every per-tensor key."""
        key = "uwr:%s" % name
        if not (self.active and self._sampling) \
                or not self.re_pattern.match(key):
            return
        new = getattr(new, "_data", new)
        old = getattr(old, "_data", old)
        if not (_concrete(new) and _concrete(old)):
            return
        self._enqueue(("scalar", self.step, key, update_ratio(new, old)))

    def note_loss(self, loss):
        """Queue the step's loss value (device scalar; multi-element
        losses are mean-reduced on device)."""
        if not (self.active and self._sampling):
            return
        data = getattr(loss, "_data", loss)
        if not _concrete(data):
            return
        if getattr(data, "ndim", 0):
            data = data.mean()
        self._enqueue(("scalar", self.step, "loss", data))

    # ---------------------------------------------------- Gluon install
    def install(self, block, prefix=""):
        """Attach forward hooks over a Gluon block tree; every watched
        output feeds :meth:`observe` as ``<path>_output<i>`` (same key
        scheme as the legacy ``Monitor``).  During a hybridize staging
        trace the hooks bail out up front (``block.is_staging``) —
        child outputs are tracers there; at steady state only the root
        hook fires, with the cached graph's concrete outputs."""
        # lazy: health loads before the gluon package finishes importing
        from .gluon.block import is_staging
        from .ndarray import NDArray

        def make_hook(name):
            def hook(_blk, _inputs, outputs):
                if is_staging():
                    return
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray):
                        self.observe("%s_output%d" % (name, i), o)
            return hook

        def attach(blk, path):
            h = blk.register_forward_hook(make_hook(path or blk.name))
            self._installed.append((blk, h))
            for k, c in blk._children.items():
                attach(c, (path + "." if path else "") + k)

        attach(block, prefix)
        return self

    def uninstall(self):
        """Remove every hook :meth:`install` attached."""
        for blk, h in self._installed:
            if h in blk._forward_hooks:
                blk._forward_hooks.remove(h)
        self._installed = []

    # ----------------------------------------------------------- clock
    def end_step(self, loss=None):
        """Advance the step clock; at sampled steps drain the queue,
        append the flight record, and run the NaN sentinel."""
        if loss is not None:
            self.note_loss(loss)
        if self._sampling:
            self.drain()
        self.step += 1
        self._sampling = (self.step % self.interval) == 0

    def drain(self):
        """Materialize every queued device value on host (ONE batched
        fetch — the layer's only sync point), fold them into per-step
        records + the flight ring, emit profiler counters, and fire the
        first-NaN warning/dump.  Returns the drained host records."""
        if not self._pending:
            return []
        t0 = time.perf_counter()
        entries = list(self._pending)
        self._pending.clear()
        host = _fetch([e[3] for e in entries])
        drained = []
        by_step: dict = {}
        for (kind, step, key, _dev), hv in zip(entries, host):
            if kind == "stats":
                rec = {"step": step, "key": key,
                       "stats": dict(zip(self.stats,
                                         (float(v) for v in hv)))}
                nan = rec["stats"]["nan_count"]
                inf = rec["stats"]["inf_count"]
            else:
                rec = {"step": step, "key": key, "value": float(hv)}
                v = rec["value"]
                nan = 1.0 if v != v else 0.0
                inf = 1.0 if (v in (float("inf"), float("-inf"))) else 0.0
            drained.append(rec)
            agg = by_step.setdefault(step, {"nan_total": 0.0,
                                            "inf_total": 0.0,
                                            "first_bad": None,
                                            "grad_norm": None,
                                            "loss": None})
            agg["nan_total"] += nan
            agg["inf_total"] += inf
            if (nan or inf) and agg["first_bad"] is None:
                agg["first_bad"] = key
            if key == "grad_norm" and kind == "scalar":
                agg["grad_norm"] = rec["value"]
            if key == "loss" and kind == "scalar":
                agg["loss"] = rec["value"]
        self.records.extend(drained)
        self.totals["drained"] += len(drained)
        probe = _rts.health_probe()
        for step in sorted(by_step):
            agg = by_step[step]
            # a mid-step drain (report()/drain() between observations)
            # must MERGE into the step's existing flight record — one
            # record per step, nan_steps counted once
            ring = self.flight._ring
            if ring and ring[-1]["step"] == step:
                rec = ring[-1]
                had_nan, had_inf = rec["nan_total"], rec["inf_total"]
                rec["time"] = time.time()
                rec["nan_total"] += agg["nan_total"]
                rec["inf_total"] += agg["inf_total"]
                if rec["first_bad"] is None:
                    rec["first_bad"] = agg["first_bad"]
                if agg["grad_norm"] is not None:
                    rec["grad_norm"] = agg["grad_norm"]
                if agg["loss"] is not None:
                    rec["loss"] = agg["loss"]
                rec["counters"] = probe
            else:
                had_nan = had_inf = 0.0
                rec = {"step": step, "time": time.time(),
                       "loss": agg["loss"],
                       "grad_norm": agg["grad_norm"],
                       "nan_total": agg["nan_total"],
                       "inf_total": agg["inf_total"],
                       "first_bad": agg["first_bad"],
                       "counters": probe}
                self.flight.append(rec)
            if agg["nan_total"] and not had_nan:
                self.totals["nan_steps"] += 1
            if agg["inf_total"] and not had_inf:
                self.totals["inf_steps"] += 1
            _profiler.counter("nan_total",
                              {"nan_total": rec["nan_total"],
                               "inf_total": rec["inf_total"]},
                              cat="health")
            if rec["grad_norm"] is not None:
                _profiler.counter("grad_norm",
                                  {"grad_norm": rec["grad_norm"]},
                                  cat="health")
            if (agg["nan_total"] or agg["inf_total"]) \
                    and self.first_nan is None:
                self.first_nan = {"step": step, "key": agg["first_bad"],
                                  "nan_total": agg["nan_total"],
                                  "inf_total": agg["inf_total"]}
        if self.first_nan is not None and not self._nan_dumped:
            self._first_nan_alarm()
        _rts.inc("health_drains")
        drain_seconds = time.perf_counter() - t0
        _rts.inc("health_seconds", drain_seconds)
        if _stepstats._state["on"]:
            # step-anatomy health_drain phase: the layer's one host
            # sync, attributed to the step window it ran in
            _stepstats.add("health_drain", drain_seconds)
        return drained

    def _first_nan_alarm(self):
        """First NaN/Inf: one rate-limited warning naming the earliest
        offending tensor, plus an atomic flight-recorder dump (the full
        diag dump when ``MXNET_TPU_DIAG`` is armed)."""
        self._nan_dumped = True
        try:
            path = self.dump("first-nan")
        except Exception:  # a failed dump must never kill training
            path = "<dump failed>"
            _logger().exception("flight-recorder dump failed")
        fn = self.first_nan
        from . import checkpoint as _ckpt

        lin = _ckpt.lineage()
        resume = ""
        if lin and lin.get("last_good_path"):
            resume = "  Last good checkpoint: %s (step %s) — resume " \
                "with checkpoint.auto_resume() (docs/CHECKPOINTING.md)." \
                % (lin["last_good_path"], lin["step"])
        warn_rate_limited(
            _logger(), "numerics-health:nan", self.warn_interval,
            "non-finite values detected at step %d: earliest offending "
            "tensor %r (%d nan, %d inf this step).  Flight recorder "
            "dumped to %s — inspect with `python -m "
            "mxnet_tpu.runtime_stats %s` (docs/OBSERVABILITY.md).%s",
            fn["step"], fn["key"], int(fn["nan_total"]),
            int(fn["inf_total"]), path, path, resume)

    # ------------------------------------------------------- read side
    def dump(self, reason=None, path=None):
        """Atomic health dump: the full diag snapshot when
        ``MXNET_TPU_DIAG`` is armed (health section included), else a
        standalone flight-recorder JSON."""
        if path is None and os.environ.get("MXNET_TPU_DIAG"):
            return _rts.dump_diag()
        return self.flight.dump(path or self.dump_path, reason=reason,
                                health=self.snapshot())

    def dump_on_crash(self):
        """Trainer.step exception hook: best-effort drain + dump (the
        ring should carry the records queued before the crash)."""
        try:
            self.drain()
        except Exception:
            pass
        try:
            warn_once(_logger(), "numerics-health:crash",
                      "unhandled exception in Trainer.step — flight "
                      "recorder dumped to %s",
                      self.dump("trainer-step-exception"))
        except Exception:
            _logger().exception("crash-path flight dump failed")

    def snapshot(self):
        """JSON-serializable view: config, totals, recent drained
        records, the flight ring, the first-NaN marker, and the
        checkpoint lineage (last-good checkpoint path + step, when the
        checkpoint layer is enabled) so a flight dump tells the
        operator exactly where to resume from.  Never syncs — pending
        device values are reported as a count only."""
        from . import checkpoint as _ckpt

        return {"enabled": _state["on"], "step": self.step,
                "interval": self.interval, "stats": list(self.stats),
                "pending": len(self._pending),
                "totals": dict(self.totals),
                "first_nan": dict(self.first_nan)
                if self.first_nan else None,
                "checkpoint": _ckpt.lineage(),
                "records": list(self.records)[-32:],
                "flight": self.flight.records()}

    def report(self):
        """Drain, then render the text section (same renderer the
        ``runtime_stats`` report/CLI uses)."""
        self.drain()
        return "\n".join(_rts._render_health(self.snapshot()))


# ------------------------------------------------------- module surface


def enable(interval=None, stats=None, pattern=".*", ring=None,
           dump_path=None, warn_interval=None):
    """Create (or replace) the global :class:`HealthMonitor` and switch
    the guard flag every feeding surface checks.  Returns the monitor."""
    mon = HealthMonitor(interval=interval, stats=stats, pattern=pattern,
                        ring=ring, dump_path=dump_path,
                        warn_interval=warn_interval)
    if _GLOBAL:
        # a replaced monitor may still have install()'d hooks attached
        # out there — deactivate it so they stop feeding a dead queue
        _GLOBAL[0].active = False
    _GLOBAL.clear()
    _GLOBAL.append(mon)
    _state["on"] = True
    return mon


def disable():
    """Stop feeding the global monitor (its records stay readable;
    install()'d hooks go inert rather than keep queueing)."""
    _state["on"] = False
    if _GLOBAL:
        _GLOBAL[0].active = False


def is_enabled():
    return _state["on"]


def monitor():
    """The global monitor while enabled, else None."""
    return _GLOBAL[0] if _state["on"] and _GLOBAL else None


def observe(key, value):
    """Feed one tensor to the global monitor (one flag check when
    disabled — safe on any hot path)."""
    if not _state["on"]:
        return
    _GLOBAL[0].observe(key, value)


def snapshot():
    """Global monitor snapshot, or a disabled stub (what
    ``runtime_stats.snapshot()['health']`` embeds)."""
    if _GLOBAL:
        return _GLOBAL[0].snapshot()
    return {"enabled": False}


def dump_flight(path=None, reason=None):
    """Dump the global monitor's flight recorder atomically; returns
    the path (None when health was never enabled)."""
    if not _GLOBAL:
        return None
    return _GLOBAL[0].dump(reason or "manual", path=path)


def reset():
    """Disable and drop the global monitor (tests)."""
    _state["on"] = False
    if _GLOBAL:
        _GLOBAL[0].active = False
    _GLOBAL.clear()
    from .log import reset_rate_limits

    reset_rate_limits("numerics-health:")


def _activate_from_env():
    if os.environ.get("MXNET_TPU_HEALTH") == "1":
        enable()
        return True
    return False


_activate_from_env()
