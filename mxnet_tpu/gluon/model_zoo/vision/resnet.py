"""ResNet v1/v2 (reference: python/mxnet/gluon/model_zoo/vision/resnet.py).

By-spec reproduction notice: the network topology (block kinds, layer
counts, channel widths, stride/downsample placement) and the parameter
naming scheme are reproduced from the papers ("Deep Residual Learning
for Image Recognition" / "Identity Mappings in Deep Residual Networks")
and the reference's Gluon module, because both the architecture and the
param names ARE the compatibility contract — checkpoints written by the
reference must load here (tests/test_backwards_compat.py).  Structural
similarity to the reference file is therefore expected; the compute
underneath is this repo's own (lax convs on the MXU, XLA
conv+bn+relu fusion under ``hybridize()``).

TPU layout option (beyond reference parity): every constructor takes
``layout="NCHW"|"NHWC"``.  NCHW (default) keeps the reference's exact
param shapes (OIHW conv weights) for checkpoint interop; NHWC stores
OHWI weights and expects NHWC input — measured ~7% faster on the
flagship training step (tools/bench_layout_experiment.py) because the
channel-last layout maps directly onto the MXU tiling with fewer HBM
relayout bytes.

ResNet-50 v1 is the flagship benchmark model (BASELINE.md: ResNet-50
ImageNet img/s).
"""

from __future__ import annotations

from ...block import HybridBlock
from ...nn import (BatchNorm, Conv2D, Dense, GlobalAvgPool2D, HybridSequential,
                   MaxPool2D, Activation)

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                  use_bias=False, in_channels=in_channels, layout=layout)


def _bn_axis(layout):
    return 3 if layout == "NHWC" else 1


class BasicBlockV1(HybridBlock):
    """18/34-layer residual block, v1 (post-activation)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(BatchNorm(axis=ax))
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(BatchNorm(axis=ax))
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1, strides=stride,
                                       use_bias=False, in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    """50/101/152-layer bottleneck block, v1."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = HybridSequential(prefix="")
        self.body.add(Conv2D(channels // 4, kernel_size=1, strides=stride,
                             layout=layout))
        self.body.add(BatchNorm(axis=ax))
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(BatchNorm(axis=ax))
        self.body.add(Activation("relu"))
        self.body.add(Conv2D(channels, kernel_size=1, strides=1, layout=layout))
        self.body.add(BatchNorm(axis=ax))
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1, strides=stride,
                                       use_bias=False, in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """18/34-layer residual block, v2 (pre-activation)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = BatchNorm(axis=ax)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """50/101/152-layer bottleneck block, v2."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = BatchNorm(axis=ax)
        self.conv1 = Conv2D(channels // 4, kernel_size=1, strides=1,
                            use_bias=False, layout=layout)
        self.bn2 = BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = BatchNorm(axis=ax)
        self.conv3 = Conv2D(channels, kernel_size=1, strides=1, use_bias=False,
                            layout=layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class _S2DStem(HybridBlock):
    """The 7×7/s2 stem conv, computed via space-to-depth (TPU MXU
    optimization, opt-in): the C=3 input leaves MXU lanes ~empty, so
    the stem's backward-filter runs at <10% MXU (BENCH_ROOFLINE.md).
    Rearranging 2×2 input blocks into channels (C: 3→12, spatial /2)
    and the 7×7 kernel into an equivalent 4×4 one computes the SAME
    function with 4× the lane occupancy.

    Derivation: out(o) = Σ_k w[k]·x[2o+k], k∈[-3,3].  Front-pad the
    kernel to 8 so K' = k+4 ∈ [1,7]; then K' = 2t+dy factors exactly
    into a (4,2) reshape — tap t∈[0,4) of a stride-1 conv over the
    s2d grid, block row dy — with the s2d input padded (2,1).  The
    parameter keeps the reference (O,7,7,I) shape, so checkpoints
    swap between stems freely; the rearrangement happens in the
    traced forward (a few KB, fused away by XLA).
    """

    def __init__(self, channels, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, 7, 7, in_channels))

    def hybrid_forward(self, F, x, weight):
        if not hasattr(x, "shape"):  # symbolic trace: Symbol has no shape
            raise NotImplementedError(
                "stem_s2d runs on the hybrid/ndarray path (GluonTrainStep, "
                "hybridize); for export/SymbolBlock build the model with "
                "stem_s2d=False — the parameter shapes are identical, so "
                "the same checkpoint loads either way")
        c_in = self.weight.shape[3]
        # kernel: (O,7,7,I) -> front-pad spatial to 8 -> (O,4,2,4,2,I)
        # -> (O,4,4,2,2,I) -> (O,4,4,4I) with channel order (dy,dx,c)
        w = F.pad(weight, mode="constant",
                  pad_width=(0, 0, 1, 0, 1, 0, 0, 0))
        w = w.reshape((self._channels, 4, 2, 4, 2, c_in))
        w = w.transpose((0, 1, 3, 2, 4, 5))
        w = w.reshape((self._channels, 4, 4, 4 * c_in))
        # input: NHWC (B,H,W,C) -> (B,H/2,W/2,4C), same (dy,dx,c) order
        b, h, ww_, c = x.shape
        if h % 2 or ww_ % 2:
            raise ValueError(
                "stem_s2d needs even spatial dims, got %dx%d — pad the "
                "input or use the standard stem (same checkpoint loads)"
                % (h, ww_))
        xs = x.reshape((b, h // 2, 2, ww_ // 2, 2, c))
        xs = xs.transpose((0, 1, 3, 2, 4, 5))
        xs = xs.reshape((b, h // 2, ww_ // 2, 4 * c))
        # asymmetric (2,1) padding in s2d space = the original pad 3
        xs = F.pad(xs, mode="constant",
                   pad_width=(0, 0, 2, 1, 2, 1, 0, 0))
        return F.Convolution(xs, w, no_bias=True, kernel=(4, 4),
                             stride=(1, 1), pad=(0, 0),
                             num_filter=self._channels, layout="NHWC")


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        if stem_s2d and layout != "NHWC":
            raise ValueError("stem_s2d requires layout='NHWC'")
        if stem_s2d and thumbnail:
            raise ValueError("stem_s2d applies to the 7x7/s2 stem; "
                             "thumbnail models have a 3x3/s1 stem")
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                if stem_s2d:
                    self.features.add(_S2DStem(channels[0],
                                               prefix="conv0_"))
                else:
                    self.features.add(Conv2D(channels[0], 7, 2, 3,
                                             use_bias=False,
                                             layout=layout))
                self.features.add(BatchNorm(axis=_bn_axis(layout)))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i], layout=layout))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.output = Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(BatchNorm(axis=ax, scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                         layout=layout))
                self.features.add(BatchNorm(axis=ax))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels, layout=layout))
                in_channels = channels[i + 1]
            self.features.add(BatchNorm(axis=ax))
            self.features.add(Activation("relu"))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.output = Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    assert num_layers in resnet_spec
    assert version in (1, 2)
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise RuntimeError("pretrained weights unavailable: no network egress")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
