"""Inception-BN (GoogLeNet v2: Ioffe & Szegedy 2015).

Reference: example/image-classification/symbols/inception-bn.py — the
network behind the Inception-BN column of the reference's published
perf tables (docs/faq/perf.md:60,171).  The reference defines it only
as a symbol graph; here it is a Gluon block (hybridizable, layout-
aware) so it plugs into the same zoo/benchmark machinery as the other
five published networks.  Topology constants (filter counts per
inception module, avg/max pool choice per stage) follow that file; the
compute underneath is this repo's own lax/XLA path.
"""

from __future__ import annotations

from ...contrib.nn import HybridConcurrent
from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Flatten,
                   HybridSequential, MaxPool2D)

__all__ = ["InceptionBN", "inception_bn"]

_BN_EPS = 1e-10 + 1e-5  # reference inception-bn.py:31


def _bn_axis(layout):
    return 3 if layout == "NHWC" else 1


def _conv_bn_relu(channels, kernel, stride=1, padding=0, layout="NCHW"):
    out = HybridSequential(prefix="")
    out.add(Conv2D(channels, kernel_size=kernel, strides=stride,
                   padding=padding, layout=layout))
    out.add(BatchNorm(axis=_bn_axis(layout), epsilon=_BN_EPS, momentum=0.9))
    out.add(Activation("relu"))
    return out


def _inception_a(num_1x1, num_3x3red, num_3x3, num_d3x3red, num_d3x3,
                 pool, proj, layout):
    """InceptionFactoryA: 1x1 | 1x1->3x3 | 1x1->3x3->3x3 | pool->1x1."""
    out = HybridConcurrent(axis=_bn_axis(layout), prefix="")
    b1 = HybridSequential(prefix="")
    b1.add(_conv_bn_relu(num_1x1, 1, layout=layout))
    b2 = HybridSequential(prefix="")
    b2.add(_conv_bn_relu(num_3x3red, 1, layout=layout))
    b2.add(_conv_bn_relu(num_3x3, 3, padding=1, layout=layout))
    b3 = HybridSequential(prefix="")
    b3.add(_conv_bn_relu(num_d3x3red, 1, layout=layout))
    b3.add(_conv_bn_relu(num_d3x3, 3, padding=1, layout=layout))
    b3.add(_conv_bn_relu(num_d3x3, 3, padding=1, layout=layout))
    b4 = HybridSequential(prefix="")
    pool_cls = AvgPool2D if pool == "avg" else MaxPool2D
    b4.add(pool_cls(pool_size=3, strides=1, padding=1, layout=layout))
    b4.add(_conv_bn_relu(proj, 1, layout=layout))
    for b in (b1, b2, b3, b4):
        out.add(b)
    return out


def _inception_b(num_3x3red, num_3x3, num_d3x3red, num_d3x3, layout):
    """InceptionFactoryB (downsample): 1x1->3x3/2 | 1x1->3x3->3x3/2 |
    maxpool/2."""
    out = HybridConcurrent(axis=_bn_axis(layout), prefix="")
    b1 = HybridSequential(prefix="")
    b1.add(_conv_bn_relu(num_3x3red, 1, layout=layout))
    b1.add(_conv_bn_relu(num_3x3, 3, stride=2, padding=1, layout=layout))
    b2 = HybridSequential(prefix="")
    b2.add(_conv_bn_relu(num_d3x3red, 1, layout=layout))
    b2.add(_conv_bn_relu(num_d3x3, 3, padding=1, layout=layout))
    b2.add(_conv_bn_relu(num_d3x3, 3, stride=2, padding=1, layout=layout))
    b3 = HybridSequential(prefix="")
    b3.add(MaxPool2D(pool_size=3, strides=2, padding=1, layout=layout))
    for b in (b1, b2, b3):
        out.add(b)
    return out


class InceptionBN(HybridBlock):
    """224x224 Inception-BN classifier (reference
    symbols/inception-bn.py get_symbol, height > 28 path)."""

    def __init__(self, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.layout = layout
        with self.name_scope():
            f = self.features = HybridSequential(prefix="")
            # stage 1
            f.add(_conv_bn_relu(64, 7, stride=2, padding=3, layout=layout))
            f.add(MaxPool2D(pool_size=3, strides=2, layout=layout))
            # stage 2
            f.add(_conv_bn_relu(64, 1, layout=layout))
            f.add(_conv_bn_relu(192, 3, padding=1, layout=layout))
            f.add(MaxPool2D(pool_size=3, strides=2, layout=layout))
            # stage 3
            f.add(_inception_a(64, 64, 64, 64, 96, "avg", 32, layout))
            f.add(_inception_a(64, 64, 96, 64, 96, "avg", 64, layout))
            f.add(_inception_b(128, 160, 64, 96, layout))
            # stage 4
            f.add(_inception_a(224, 64, 96, 96, 128, "avg", 128, layout))
            f.add(_inception_a(192, 96, 128, 96, 128, "avg", 128, layout))
            f.add(_inception_a(160, 128, 160, 128, 160, "avg", 128, layout))
            f.add(_inception_a(96, 128, 192, 160, 192, "avg", 128, layout))
            f.add(_inception_b(128, 192, 192, 256, layout))
            # stage 5
            f.add(_inception_a(352, 192, 320, 160, 224, "avg", 128, layout))
            f.add(_inception_a(352, 192, 320, 192, 224, "max", 128, layout))
            f.add(AvgPool2D(pool_size=7, strides=1, layout=layout))
            f.add(Flatten())
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_bn(pretrained=False, ctx=None, root=None, **kwargs):
    if pretrained:
        raise ValueError("no pretrained inception_bn weights ship with "
                         "this framework")
    return InceptionBN(**kwargs)
