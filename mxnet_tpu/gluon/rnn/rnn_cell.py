"""Gluon RNN cells.

Reference: python/mxnet/gluon/rnn/rnn_cell.py (RecurrentCell, RNNCell,
LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, ZoneoutCell,
ResidualCell, BidirectionalCell).

Cells compute one time step; `unroll` loops steps in Python — under
``hybridize()`` the whole unrolled graph stages into one XLA module.
The fused rnn_layer.RNN/LSTM/GRU (lax.scan) is the fast path for long
sequences.
"""

from __future__ import annotations

from ... import ndarray
from ...base import numeric_types
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, batch_size=0, **kwargs):
    return sum([c.begin_state(batch_size, **kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, ndarray.NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is None:
                length = inputs.shape[axis]
            inputs = list(ndarray.imperative_invoke(
                "SliceChannel", [inputs],
                {"num_outputs": length, "axis": axis, "squeeze_axis": True}))
    else:
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = [i.expand_dims(axis) for i in inputs]
            inputs = ndarray.concatenate(inputs, axis=axis)
    if isinstance(inputs, list):
        length = len(inputs)
    return inputs, axis, batch_size, length


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    if isinstance(data, list):
        stacked = ndarray.stack_arrays(data, axis=time_axis)
    else:
        stacked = data
    outputs = F.SequenceMask(stacked, sequence_length=valid_length,
                             use_sequence_length=True, axis=time_axis)
    if isinstance(data, list) and not merge:
        return list(ndarray.imperative_invoke(
            "SliceChannel", [outputs],
            {"num_outputs": length, "axis": time_axis, "squeeze_axis": True}))
    return outputs


class RecurrentCell(Block):
    """Base class for recurrent cells (reference: rnn_cell.py:60)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference: rnn_cell.py begin_state)."""
        assert not self._modified
        if func is None:
            func = ndarray.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **{**info, **kwargs}))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell `length` steps (reference: rnn_cell.py unroll)."""
        self.reset()
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        from ... import ndarray as F

        if valid_length is not None:
            states = [ndarray.imperative_invoke(
                "SequenceLast",
                [ndarray.stack_arrays([s[i] for s in all_states], axis=0),
                 valid_length],
                {"use_sequence_length": True, "axis": 0})[0]
                for i in range(len(states))]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
            if merge_outputs is False:
                outputs = list(ndarray.imperative_invoke(
                    "SliceChannel", [outputs],
                    {"num_outputs": length, "axis": axis, "squeeze_axis": True}))
        elif merge_outputs:
            outputs = ndarray.stack_arrays(outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell that supports hybridize (reference: rnn_cell.py)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_i x + b_i + W_h h + b_h)
    (reference: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, cuDNN gate order (i, f, g, o)
    (reference: rnn_cell.py LSTMCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slice_gates[0])
        forget_gate = F.sigmoid(slice_gates[1])
        in_transform = F.tanh(slice_gates[2])
        out_gate = F.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, cuDNN gate order (r, z, n)
    (reference: rnn_cell.py GRUCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h + reset_gate * h2h)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        num_cells = len(self._children)
        begin_state = begin_state if begin_state is not None else \
            _cells_begin_state(self._children.values(),
                               batch_size=_batch_size(inputs, layout))
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class HybridSequentialRNNCell(HybridRecurrentCell):
    """Hybrid stack of cells."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    unroll = SequentialRNNCell.unroll
    __getitem__ = SequentialRNNCell.__getitem__
    __len__ = SequentialRNNCell.__len__


def _batch_size(inputs, layout):
    batch_axis = layout.find("N")
    if isinstance(inputs, ndarray.NDArray):
        return inputs.shape[batch_axis]
    return inputs[0].shape[batch_axis]


class _ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py)."""

    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + "_modifier_", params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size,
                                           func=func or ndarray.zeros, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(HybridRecurrentCell):
    """Apply dropout on input (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, numeric_types)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ZoneoutCell(_ModifierCell):
    """Zoneout state regularization (reference: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = ndarray.zeros(next_output.shape,
                                        ctx=next_output.context)
        output = F.where(mask(self.zoneout_outputs, next_output), next_output,
                         prev_output) if self.zoneout_outputs > 0. else next_output
        new_states = [F.where(mask(self.zoneout_states, ns), ns, s)
                      for ns, s in zip(next_states, states)] \
            if self.zoneout_states > 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(_ModifierCell):
    """Add skip connection around a cell (reference: rnn_cell.py)."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        if isinstance(outputs, list):
            inputs, _, _, _ = _format_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, inputs)]
        else:
            inputs, _, _, _ = _format_sequence(length, inputs, layout, True)
            outputs = outputs + inputs
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells fwd/bwd over a sequence (reference: rnn_cell.py)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cell cannot be stepped; use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        reversed_inputs = list(reversed(inputs))
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            r_outputs = list(reversed(r_outputs))
        else:
            r_outputs = list(reversed(r_outputs))
        outputs = [ndarray.concatenate([l, r], axis=1)
                   for l, r in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = ndarray.stack_arrays(outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
