"""Fused multi-layer RNN/LSTM/GRU Gluon layers.

Reference: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer over the
monolithic RNN op src/operator/rnn.cc).  The compute is ops/rnn.py's
lax.scan kernel; parameters are kept as separate Gluon Parameters
(l0_i2h_weight, ...) and packed into the cuDNN flat layout at forward,
matching the reference's parameter naming for checkpoint parity.
"""

from __future__ import annotations

from ... import autograd, ndarray
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        self._mode = mode  # before super(): _alias() runs in Block.__init__
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("%s%d_i2h_weight" % (j, i),
                                     (ng * nh, ni), i2h_weight_initializer)
                self._register_param("%s%d_h2h_weight" % (j, i),
                                     (ng * nh, nh), h2h_weight_initializer)
                self._register_param("%s%d_i2h_bias" % (j, i),
                                     (ng * nh,), i2h_bias_initializer)
                self._register_param("%s%d_h2h_bias" % (j, i),
                                     (ng * nh,), h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        return "%s(%s, %s layers, hidden=%s%s)" % (
            type(self).__name__, self._layout, self._num_layers,
            self._hidden_size, ", bidirectional" if self._dir == 2 else "")

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[2]
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                p = getattr(self, "%s%d_i2h_weight" % (j, i))
                p.shape = (self._gates * self._hidden_size, ni)
            ni = self._hidden_size * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if func is None:
            func = ndarray.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **{**info, **kwargs}))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if isinstance(states, ndarray.NDArray):
            states = [states]
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context,
                                      dtype=inputs.dtype)
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)

        # pack parameters into the cuDNN flat layout: all weights
        # (layer-major, i2h then h2h), then all biases
        ws = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(params["%s%d_i2h_weight" % (j, i)].reshape((-1,)))
                ws.append(params["%s%d_h2h_weight" % (j, i)].reshape((-1,)))
        bs = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(params["%s%d_i2h_bias" % (j, i)])
                bs.append(params["%s%d_h2h_bias" % (j, i)])
        flat = F.Concat(*(ws + bs), dim=0)

        rnn_args = [inputs, flat] + states
        out = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs, states_out = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, states_out


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu/tanh) (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
