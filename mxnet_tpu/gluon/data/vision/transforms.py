"""Vision transforms (reference: python/mxnet/gluon/data/vision/
transforms.py: Compose, Cast, ToTensor, Normalize, Resize, CenterCrop,
RandomResizedCrop, RandomFlipLeftRight, ...).

Transforms run on host numpy (cheap per-sample work in DataLoader
workers); the batched result is device_put once.
"""

from __future__ import annotations

import numpy as _np

from .... import ndarray
from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomLighting", "RandomColorJitter"]


def _as_np(x):
    return x.asnumpy() if isinstance(x, ndarray.NDArray) else _np.asarray(x)


class Compose(Sequential):
    """Chain transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: transforms.py
    ToTensor over src/operator/image/totensor)."""

    def __init__(self):
        super().__init__()

    def forward(self, x):
        arr = _as_np(x).astype(_np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return ndarray.array(arr)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x):
        arr = _as_np(x)
        mean = self._mean.reshape((-1, 1, 1)) if self._mean.ndim else self._mean
        std = self._std.reshape((-1, 1, 1)) if self._std.ndim else self._std
        return ndarray.array((arr - mean) / std)


def _resize_np(arr, size, interp="bilinear"):
    """Bilinear resize HWC uint8/float via pure numpy."""
    h, w = arr.shape[:2]
    if isinstance(size, int):
        size = (size, size)
    ow, oh = size  # reference order: (width, height)
    if (oh, ow) == (h, w):
        return arr
    ys = _np.linspace(0, h - 1, oh)
    xs = _np.linspace(0, w - 1, ow)
    y0 = _np.floor(ys).astype(_np.int64)
    x0 = _np.floor(xs).astype(_np.int64)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    a = arr[_np.ix_(y0, x0)].astype(_np.float32)
    b = arr[_np.ix_(y0, x1)].astype(_np.float32)
    c = arr[_np.ix_(y1, x0)].astype(_np.float32)
    d = arr[_np.ix_(y1, x1)].astype(_np.float32)
    out = a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx + \
        c * wy * (1 - wx) + d * wy * wx
    if arr.dtype == _np.uint8:
        out = _np.clip(_np.rint(out), 0, 255).astype(_np.uint8)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        arr = _as_np(x)
        size = self._size
        if self._keep and isinstance(size, int):
            h, w = arr.shape[:2]
            if h < w:
                size = (int(w * size / h), size)
            else:
                size = (size, int(h * size / w))
        return ndarray.array(_resize_np(arr, size), dtype=arr.dtype)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        arr = _as_np(x)
        ow, oh = self._size
        h, w = arr.shape[:2]
        if h < oh or w < ow:
            arr = _resize_np(arr, (max(ow, w), max(oh, h)))
            h, w = arr.shape[:2]
        y = (h - oh) // 2
        xo = (w - ow) // 2
        return ndarray.array(arr[y:y + oh, xo:xo + ow], dtype=arr.dtype)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        arr = _as_np(x)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            nw = int(round(_np.sqrt(target_area * aspect)))
            nh = int(round(_np.sqrt(target_area / aspect)))
            if nw <= w and nh <= h:
                y = _np.random.randint(0, h - nh + 1)
                xo = _np.random.randint(0, w - nw + 1)
                crop = arr[y:y + nh, xo:xo + nw]
                return ndarray.array(_resize_np(crop, self._size),
                                     dtype=arr.dtype)
        return CenterCrop(self._size).forward(ndarray.array(arr, dtype=arr.dtype))


class RandomFlipLeftRight(Block):
    def __init__(self):
        super().__init__()

    def forward(self, x):
        if _np.random.rand() < 0.5:
            return ndarray.array(_as_np(x)[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def __init__(self):
        super().__init__()

    def forward(self, x):
        if _np.random.rand() < 0.5:
            return ndarray.array(_as_np(x)[::-1].copy())
        return x


class _RandomJitter(Block):
    def __init__(self, magnitude):
        super().__init__()
        self._m = magnitude

    def _alpha(self):
        return 1.0 + _np.random.uniform(-self._m, self._m)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        arr = _as_np(x).astype(_np.float32) * self._alpha()
        return ndarray.array(arr)


class RandomContrast(_RandomJitter):
    def forward(self, x):
        arr = _as_np(x).astype(_np.float32)
        gray = arr.mean()
        return ndarray.array(gray + (arr - gray) * self._alpha())


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        arr = _as_np(x).astype(_np.float32)
        gray = arr.mean(axis=-1, keepdims=True)
        return ndarray.array(gray + (arr - gray) * self._alpha())


class RandomHue(Block):
    """YIQ hue rotation by a random angle in [-hue, hue] (reference:
    transforms.py RandomHue over image.py HueJitterAug)."""

    _tyiq = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], dtype=_np.float32)
    _ityiq = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], dtype=_np.float32)

    def __init__(self, hue):
        super().__init__()
        self._hue = hue

    def forward(self, x):
        alpha = _np.random.uniform(-self._hue, self._hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       dtype=_np.float32)
        t = (self._ityiq @ bt @ self._tyiq).T
        arr = _as_np(x).astype(_np.float32)
        return ndarray.array(arr @ t)


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: transforms.py)."""

    _eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        arr = _as_np(x).astype(_np.float32)
        alpha = _np.random.normal(0, self._alpha, size=(3,)).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return ndarray.array(arr + rgb)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = _np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i].forward(x)
        return x
