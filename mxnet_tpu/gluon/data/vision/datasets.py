"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py:
MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset,
ImageFolderDataset).

No network egress in this environment: datasets read pre-downloaded
files from ``root`` when present, else raise with instructions; use
``SyntheticImageDataset`` for tests/benchmarks.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from .... import ndarray, recordio
from ....base import np_dtype
from ..dataset import ArrayDataset, Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset",
           "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from pre-downloaded idx-gz files (reference: datasets.py
    MNIST)."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        image_file, label_file = self._train_files if self._train \
            else self._test_files
        image_path = os.path.join(self._root, image_file)
        label_path = os.path.join(self._root, label_file)
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise RuntimeError(
                "MNIST files not found under %s (no network egress; place "
                "%s and %s there, or use SyntheticImageDataset)" %
                (self._root, image_file, label_file))
        with gzip.open(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            label = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)
        with gzip.open(image_path, "rb") as f:
            _, _, rows, cols = struct.unpack(">IIII", f.read(16))
            data = _np.frombuffer(f.read(), dtype=_np.uint8)
            data = data.reshape(len(label), rows, cols, 1)
        self._label = label
        self._data = [ndarray.array(x, dtype="uint8") for x in data]


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python pickle tarball (reference: datasets.py
    CIFAR10)."""

    _archive = "cifar-10-python.tar.gz"
    _folder = "cifar-10-batches-py"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, fobj):
        d = pickle.load(fobj, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        labels = d.get(b"labels", d.get(b"fine_labels"))
        return data, _np.asarray(labels, dtype=_np.int32)

    def _batches(self):
        if self._train:
            return ["data_batch_%d" % i for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        folder = os.path.join(self._root, self._folder)
        archive = os.path.join(self._root, self._archive)
        datas, labels = [], []
        if os.path.isdir(folder):
            for b in self._batches():
                with open(os.path.join(folder, b), "rb") as f:
                    d, l = self._read_batch(f)
                datas.append(d)
                labels.append(l)
        elif os.path.exists(archive):
            with tarfile.open(archive) as tf:
                for b in self._batches():
                    f = tf.extractfile("%s/%s" % (self._folder, b))
                    d, l = self._read_batch(f)
                    datas.append(d)
                    labels.append(l)
        else:
            raise RuntimeError(
                "CIFAR10 files not found under %s (no network egress; place "
                "%s there, or use SyntheticImageDataset)" %
                (self._root, self._archive))
        data = _np.concatenate(datas)
        self._label = _np.concatenate(labels)
        self._data = [ndarray.array(x, dtype="uint8") for x in data]


class CIFAR100(CIFAR10):
    _archive = "cifar-100-python.tar.gz"
    _folder = "cifar-100-python"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batches(self):
        return ["train"] if self._train else ["test"]

    def _read_batch(self, fobj):
        d = pickle.load(fobj, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        return data, _np.asarray(d[key], dtype=_np.int32)


class SyntheticImageDataset(Dataset):
    """Deterministic random images+labels — for tests and benchmarks in
    egress-free environments (TPU-native addition; parity datasets above
    need the real files)."""

    def __init__(self, length=1024, shape=(32, 32, 3), num_classes=10,
                 transform=None, seed=0):
        self._length = length
        rng = _np.random.RandomState(seed)
        self._images = rng.randint(0, 256, size=(length,) + tuple(shape),
                                   dtype=_np.uint8)
        self._labels = rng.randint(0, num_classes, size=(length,),
                                   ).astype(_np.int32)
        self._transform = transform

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        img = ndarray.array(self._images[idx], dtype="uint8")
        label = self._labels[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageRecordDataset(RecordFileDataset):
    """Dataset over an image RecordIO file (reference: datasets.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super().__getitem__(idx)
        header, img_bytes = recordio.unpack(record)
        from .... import image as _image

        img = _image.imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Folder-of-class-folders image dataset (reference: datasets.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image as _image

        with open(self.items[idx][0], "rb") as f:
            img = _image.imdecode(f.read(), flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
