"""Gluon DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:464 (DataLoader with
multiprocessing workers + shared-memory NDArray rebuild, default
batchify).

TPU-native notes: the reference forks worker processes and ships
batches through shared-memory NDArrays; here workers are a thread pool
(JPEG decode / numpy augmentation release the GIL) and the assembled
host batch is device_put once — the single host→HBM transfer per batch
the TPU input pipeline wants.  ``num_workers>0`` enables a prefetching
background pipeline (the reference PrefetcherIter double-buffer,
src/io/iter_prefetcher.h:47).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ... import ndarray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], ndarray.NDArray):
        return ndarray.stack_arrays(list(data))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = _np.asarray(data)
    return ndarray.array(data, dtype=data.dtype)


class DataLoader:
    """Iterate a Dataset in mini-batches (reference: dataloader.py:464)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with a custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be set "
                "when batch_sampler is specified")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        yield from self._prefetch_iter()

    def _prefetch_iter(self):
        """Background-assembled batches, bounded queue double-buffer."""
        pool = ThreadPoolExecutor(max_workers=self._num_workers)
        out_q = queue.Queue(maxsize=max(2, self._prefetch))
        stop = threading.Event()

        def producer():
            try:
                futures = []
                for indices in self._batch_sampler:
                    if stop.is_set():
                        return
                    futures.append(pool.submit(self._load_batch, indices))
                    while len(futures) >= max(2, self._prefetch):
                        out_q.put(("ok", futures.pop(0).result()))
                for f in futures:
                    out_q.put(("ok", f.result()))
                out_q.put(("done", None))
            except Exception as e:  # propagate to consumer
                out_q.put(("err", e))

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                kind, val = out_q.get()
                if kind == "done":
                    return
                if kind == "err":
                    raise val
                yield val
        finally:
            stop.set()
            pool.shutdown(wait=False)
