"""Gluon — the imperative/hybrid high-level API.

Reference: python/mxnet/gluon/ (Block/HybridBlock, Parameter, Trainer,
nn/rnn layer libraries, loss, data, model_zoo).
"""

from .parameter import Parameter, ParameterDict, Constant  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import utils  # noqa: F401
from .utils import split_and_load  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import rnn  # noqa: F401
from . import data  # noqa: F401
from . import model_zoo  # noqa: F401
from . import contrib  # noqa: F401
