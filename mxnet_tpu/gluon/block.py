"""Gluon Block / HybridBlock / SymbolBlock.

Reference: python/mxnet/gluon/block.py (Block:127, HybridBlock:671,
hybridize:504, _build_cache:748 -> CachedOp, export:868, SymbolBlock:952).

TPU-native design
-----------------
The reference's ``hybridize()`` traces ``hybrid_forward`` with Symbols
and builds a C++ CachedOp that caches fwd+bwd nnvm graphs per input
signature (src/imperative/cached_op.cc:266,842).  Here hybridize stages
the same ``hybrid_forward`` — run with real NDArrays whose buffers are
jax tracers — into ONE jitted XLA computation per input signature:

- signature key = input shapes/dtypes + train-mode flag (exactly the
  CachedOp SetForwardGraph signature match);
- parameters enter as traced arguments (so one executable serves every
  step — no retrace on update);
- randomness (Dropout) derives from a traced seed via random.TraceRNG,
  so compiled graphs get fresh keys without retracing;
- BatchNorm-style running-stat updates are collected as extra traced
  outputs (the `_StagingScope.aux_updates` channel) and written back
  eagerly — keeping the staged function pure for XLA;
- under ``autograd.record()``, backward is a second cached jitted
  function computing vjp-with-recompute (XLA remat of the forward),
  registered on the imperative tape like any other op.
"""

from __future__ import annotations

import re
import threading

import numpy as _np

from .. import autograd, initializer, ndarray
from .. import random as _random
from .. import xray as _xray
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray
from .parameter import (DeferredInitializationError, Parameter, ParameterDict,
                        param_override)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "is_staging",
           "staged_call"]


class _BlockScope:
    """Name scoping for Blocks (reference: gluon/block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_unique(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block._params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_NAME_COUNTER = {}


def _name_unique(hint):
    count = _NAME_COUNTER.get(hint, 0)
    _NAME_COUNTER[hint] = count + 1
    return "%s%d" % (hint, count)


class Block:
    """Base class for all neural-network layers and models
    (reference: gluon/block.py:127)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------ attrs
    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError("Changing attribute type for %s from %s to %s "
                                "is not allowed." % (name, type(existing), type(value)))
        if isinstance(value, Block):
            self._children[name] = value
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    # ------------------------------------------------------------ info
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def __repr__(self):
        s = "{name}(\n{body}\n)" if self._children else "{name}()"
        body = "\n".join("  (%s): %s" % (k, _indent(repr(v)))
                         for k, v in self._children.items())
        return s.format(name=self.__class__.__name__, body=body)

    def collect_aux_losses(self):
        """Sum the ``aux_loss`` of every descendant block that exposes
        one (MoE load-balancing losses today; any block may publish an
        ``aux_loss`` property holding its most recent forward's
        auxiliary loss).

        Call after the forward, inside the same autograd/staging scope
        — or let ``GluonTrainStep(aux_loss_weight=w)`` do both the
        collection and the weighting for you.  Raises if no descendant
        publishes an aux loss (a silent 0.0 would hide a wiring bug).
        """
        total = None
        stack = [self]
        seen = set()  # a shared block reachable twice contributes once
        while stack:
            b = stack.pop()
            if id(b) in seen:
                continue
            seen.add(id(b))
            aux = getattr(type(b), "aux_loss", None)
            if aux is not None:
                val = b.aux_loss
                total = val if total is None else total + val
            stack.extend(b._children.values())
        if total is None:
            raise ValueError(
                "no descendant of %r publishes an aux_loss" % (self,))
        return total

    # ------------------------------------------------------------ params
    def collect_params(self, select=None):
        """All Parameters of this block and its descendants, optionally
        filtered by a regex over names (reference: Block.collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        """Structural (attribute-path) parameter names, used by
        save_parameters/load_parameters (reference: block.py)."""
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        from .. import initializer as _init

        self.collect_params().initialize(init or _init.Uniform(), ctx,
                                         verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------ save/load
    def save_parameters(self, filename):
        """Save parameters by structural name (reference:
        block.py save_parameters) — atomically via
        ``checkpoint.atomic_write`` so a crash mid-save can never leave
        a torn params file under the final name."""
        from ..checkpoint import atomic_write

        params = self._collect_params_with_prefix()
        arg_dict = {k: v.data().as_in_context(cpu()) for k, v in params.items()}
        with atomic_write(filename) as tmp:
            ndarray.save(tmp, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        loaded = ndarray.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # legacy files saved with full prefixed names
        if loaded and not any("." in k for k in loaded.keys()) and \
                any("." in k for k in params.keys()):
            loaded = {k.replace(self.prefix, "", 1) if k.startswith(self.prefix)
                      else k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise IOError("Parameter %s is missing in file %s"
                                  % (name, filename))
        for name, value in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise IOError("Parameter %s in file %s is not present in "
                                  "this Block" % (name, filename))
                continue
            p = params[name]
            if p._data is None:
                p.shape = tuple(value.shape)
                if p._deferred_init:
                    p._finish_deferred_init(value.shape)
                else:
                    p.initialize(ctx=p._ctx_list or ctx or [current_context()])
            if cast_dtype:
                p.cast(value.dtype)
            p.set_data(value)

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------ run
    def __call__(self, *args):
        # fused-step x-ray: inside a staging trace, each block's forward
        # runs under a named scope so the compiled program's HLO carries
        # the block path in op_name metadata (xray.analyze attributes
        # per-instruction cost back to it).  Off OR eager = one dict
        # read + the is_staging check — nothing on the eager hot path.
        if _xray._state["on"] and is_staging():
            with _xray.block_scope(self):
                return self._hooked_forward(args)
        return self._hooked_forward(args)

    def _hooked_forward(self, args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """no-op on plain Blocks; recurses so nested HybridBlocks engage."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary (reference: block.py summary)."""
        rows = []

        def make_hook(name):
            def hook(block, ins, outs):
                out = outs[0] if isinstance(outs, (list, tuple)) else outs
                n_params = sum(_np.prod(p.shape)
                               for p in block._reg_params.values()
                               if p.shape is not None)
                rows.append((name, type(block).__name__,
                             tuple(getattr(out, "shape", ())), int(n_params)))
            return hook

        handles = []
        def attach(block, path):
            h = block.register_forward_hook(make_hook(path))
            handles.append((block, h))
            for k, c in block._children.items():
                attach(c, path + "." + k if path else k)
        attach(self, "")
        try:
            self(*inputs)
        finally:
            for b, h in handles:
                b._forward_hooks.remove(h)
        print("%-30s %-20s %-20s %s" % ("Layer", "Type", "Output", "Params"))
        total = 0
        for name, typ, shape, n in rows:
            total += n
            print("%-30s %-20s %-20s %d" % (name or "(self)", typ, shape, n))
        print("Total params: %d" % total)


def _indent(s):
    return s.replace("\n", "\n  ")


# ------------------------------------------------------------------ staging


class _StagingScope:
    """Active while a HybridBlock subtree is being traced into one XLA
    computation.  Collects aux-state updates (BatchNorm running stats) as
    traced outputs — the functional analog of the reference executor
    mutating aux NDArrays in place."""

    _current = threading.local()

    def __init__(self):
        self.aux_updates = {}   # Parameter -> traced jax value (insertion-ordered)

    def __enter__(self):
        stack = getattr(_StagingScope._current, "stack", None)
        if stack is None:
            stack = _StagingScope._current.stack = []
        stack.append(self)
        return self

    def __exit__(self, *a):
        _StagingScope._current.stack.pop()

    @classmethod
    def current(cls):
        stack = getattr(cls._current, "stack", None)
        return stack[-1] if stack else None


def is_staging():
    """True while a HybridBlock subtree is being traced into one XLA
    computation — hook code that must not leak tracers (monitors,
    health observers) checks this (or buffer concreteness) before
    queueing values across the trace boundary."""
    return _StagingScope.current() is not None


def staged_call(block, override, seed, args, train=True):
    """Run ``block(*args)`` under a fresh staging scope with parameter
    overrides and a traced RNG: the one idiom every whole-graph tracer
    shares (``parallel/gluon_step.py``'s SPMD step builder and
    ``compiled_step.py``'s whole-step program).

    ``block`` is any callable over NDArrays (a Block, or a closure
    composing forward + loss); ``override`` maps Parameter -> NDArray
    (typically tracer-backed); ``seed`` is a traced PRNG key (or None
    to keep the ambient RNG); ``args`` are NDArray inputs.  Returns
    ``(out, scope)`` where ``scope.aux_updates`` holds the traced
    auxiliary-state updates (BatchNorm running stats) collected during
    the call."""
    from .. import random as _rand

    scope = _StagingScope()
    mode = autograd.train_mode() if train else autograd.predict_mode()
    with param_override(override), scope, \
            (_rand.TraceRNG(seed) if seed is not None else _nullctx()), \
            mode:
        out = block(*args)
    return out, scope


def update_aux_state(param, new_value):
    """Write an auxiliary state (running stat): eager write normally,
    traced side-output inside a staged graph."""
    scope = _StagingScope.current()
    if scope is not None:
        scope.aux_updates[param] = (
            new_value._data if isinstance(new_value, NDArray) else new_value)
        return
    with autograd.pause():
        data = param.data()
        data._assign(new_value._data if isinstance(new_value, NDArray)
                     else new_value)


class _CachedGraph:
    """One staged (forward, backward) pair for a fixed input signature —
    the analog of CachedOp's per-signature graph cache
    (src/imperative/cached_op.cc:266)."""

    def __init__(self, block, params, template_args, is_train):
        import jax

        self.params = params            # list[Parameter], traced order
        self.aux_order = []             # list[Parameter] discovered at trace
        self.out_treedef = None
        block_ref = block

        def core(pvals, avals, seed):
            nds = [NDArray(a) for a in avals]
            override = {p: NDArray(v) for p, v in zip(params, pvals)}
            scope = _StagingScope()
            with param_override(override), scope, \
                    _random.TraceRNG(seed) if seed is not None else _nullctx():
                out = block_ref._plain_forward(*nds)
            outs = _flatten_outputs(out)
            self.out_treedef = _treedef_of(out)
            self.aux_order = list(scope.aux_updates.keys())
            aux_vals = [scope.aux_updates[p] for p in self.aux_order]
            return tuple(o._data for o in outs), tuple(aux_vals)

        self._core = core
        self._fwd = jax.jit(core)

        def bwd(pvals, avals, seed, cts):
            # vjp-with-recompute: XLA sees fwd+bwd in one module and CSEs /
            # remats (reference analog: CachedOp::SetBackwardGraph caches
            # the grad graph; mirror policy graph_executor.cc:261).
            # The recompute must re-trace under the FORWARD's train mode:
            # this jit is first traced inside backward(), outside the
            # record() scope, and without the pin BatchNorm/Dropout would
            # take their inference branches — differentiating a different
            # function than the one that produced the outputs (grads
            # through running stats instead of batch stats, dropout
            # masks dropped from the backward).
            mode = autograd.train_mode() if is_train \
                else autograd.predict_mode()
            with mode:
                _outs, vjp = jax.vjp(
                    lambda p, a: core(p, a, seed)[0], pvals, avals)
            return vjp(cts)

        self._bwd = jax.jit(bwd)
        self.is_train = is_train

    def __call__(self, block, args):
        import jax

        pvals = tuple(p.data(args[0].context if args else None)._data
                      for p in self.params)
        avals = tuple(a._data for a in args)
        seed = _random.next_key()

        recording = autograd.is_recording() and (
            _np.any([p.grad_req != "null" for p in self.params]) or
            autograd._any_recorded(args))
        outs, aux_vals = self._fwd(pvals, avals, seed)

        for p, v in zip(self.aux_order, aux_vals):
            with autograd.pause():
                p.data()._assign(v)

        ctx = args[0]._ctx if args else None
        out_nds = [NDArray(o, ctx) for o in outs]
        # numerics-health note: steady-state hybridized forward never
        # re-enters child __call__ (the whole subtree is one cached
        # executable), so per-child forward hooks can't observe — but
        # the ROOT block's forward hooks fire in Block.__call__ with
        # these concrete outputs, so an installed HealthMonitor still
        # covers the staged graph's outputs (and skips the tracer
        # values seen during the staging trace itself).

        if recording:
            param_nds = [p.data(args[0].context if args else None)
                         for p in self.params]
            bwd_jit = self._bwd

            def vjp_fn(cts):
                cts = cts if isinstance(cts, tuple) else (cts,)
                gp, ga = bwd_jit(pvals, avals, seed, tuple(cts))
                return tuple(gp) + tuple(ga)

            autograd.record_op(list(param_nds) + list(args), out_nds, vjp_fn)

        return _unflatten_outputs(out_nds, self.out_treedef)


class _nullctx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def _flatten_outputs(out):
    if isinstance(out, NDArray):
        return [out]
    if isinstance(out, (list, tuple)):
        flat = []
        for o in out:
            flat.extend(_flatten_outputs(o))
        return flat
    raise TypeError("HybridBlock output must be NDArray or (nested) list, got %s"
                    % type(out))


def _treedef_of(out):
    if isinstance(out, NDArray):
        return None
    return [_treedef_of(o) for o in out]


def _unflatten_outputs(flat, treedef):
    it = iter(flat)

    def build(td):
        if td is None:
            return next(it)
        return [build(t) for t in td]

    return build(treedef)


class HybridBlock(Block):
    """A Block that can be staged into one compiled XLA graph
    (reference: gluon/block.py:671)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graphs = {}
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._cached_graphs = {}
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_graphs = {}
        super().cast(dtype)

    def infer_shape(self, *args):
        """Deferred-shape resolution hook; leaf layers override."""
        raise NotImplementedError(
            "%s has deferred-initialized parameters whose shape could not "
            "be inferred; implement infer_shape() or initialize with full "
            "shapes." % type(self).__name__)

    # ------------------------------------------------------------ forward
    def forward(self, x, *args):
        from .. import symbol as _sym

        if isinstance(x, _sym.Symbol):
            params = {k: p.var() for k, p in self._reg_params.items()}
            with _name_prefix_scope(self._prefix):
                return self.hybrid_forward(_sym, x, *args, **params)
        if not isinstance(x, NDArray):
            raise TypeError("HybridBlock input must be NDArray or Symbol, got %s"
                            % type(x))
        if self._active and _StagingScope.current() is None:
            return self._call_cached(x, *args)
        return self._plain_forward(x, *args)

    def _plain_forward(self, x, *args):
        ctx = x.context
        try:
            params = {k: p.data(ctx) for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_init_params(x, *args)
            params = {k: p.data(ctx) for k, p in self._reg_params.items()}
        return self.hybrid_forward(ndarray, x, *args, **params)

    def _deferred_init_params(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def _call_cached(self, *args):
        # warm any deferred params across the subtree with one eager pass
        key = (tuple((a.shape, str(a.dtype)) for a in args),
               autograd.is_training())
        graph = self._cached_graphs.get(key)
        if graph is None:
            try:
                params = list(self.collect_params().values())
                for p in params:
                    p._check_initialized()
            except DeferredInitializationError:
                with autograd.pause():
                    self._plain_forward(*args)
                params = list(self.collect_params().values())
            graph = _CachedGraph(self, params, args, autograd.is_training())
            self._cached_graphs[key] = graph
        return graph(self, args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------ export
    def export(self, path, epoch=0):
        """Export to symbol JSON + params, loadable by SymbolBlock /
        Module (reference: HybridBlock.export block.py:868)."""
        from .. import symbol as _sym

        inp = _sym.Variable("data")
        out = self(inp)
        if isinstance(out, (list, tuple)):
            out = _sym.Group(list(out))
        out.save("%s-symbol.json" % path)
        aux_names = set(out.list_auxiliary_states())
        arg_dict = {}
        for name, param in self.collect_params().items():
            kind = "aux" if name in aux_names else "arg"
            arg_dict["%s:%s" % (kind, name)] = param.data().as_in_context(cpu())
        ndarray.save("%s-%04d.params" % (path, epoch), arg_dict)
        return out


class _name_prefix_scope:
    """Route auto-generated symbol node names under the block prefix."""

    def __init__(self, prefix):
        from ..base import NameManager
        self._prefix = prefix
        self._nm = NameManager

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a Block for imperative use
    (reference: gluon/block.py SymbolBlock:952)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=None)
        from .. import symbol as _sym

        if isinstance(outputs, (list, tuple)):
            outputs = _sym.Group(list(outputs))
        if isinstance(inputs, _sym.Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = [n for n in outputs.list_arguments()
                     if n not in self._input_names]
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names:
            p = Parameter(name, allow_deferred_init=True)
            self._params._params[name] = p
        for name in outputs.list_auxiliary_states():
            p = Parameter(name, grad_req="null", allow_deferred_init=True)
            self._params._params[name] = p
        if params is not None:
            for name, v in params.items():
                clean = name
                if name.startswith(("arg:", "aux:")):
                    clean = name[4:]
                if clean in self._params._params:
                    p = self._params._params[clean]
                    p.shape = tuple(v.shape)
                    p.dtype = v.dtype
                    # values are set right below — zero-init avoids the
                    # name-pattern initializer (e.g. *_quantize params)
                    p.initialize(init=initializer.Zero(), ctx=v.context)
                    p.set_data(v)
        self._fn_cache = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported model (reference: SymbolBlock.imports)."""
        from .. import symbol as _sym

        sym = _sym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym.Variable(n) for n in input_names]
        params = ndarray.load(param_file) if param_file else None
        if params is not None and ctx is not None:
            params = {k: v.as_in_context(ctx) for k, v in params.items()}
        return SymbolBlock(sym, inputs, params=params)

    def forward(self, *args):
        import jax

        from ..executor import make_eval_fn

        is_train = autograd.is_training()
        entry = self._fn_cache.get(is_train)
        if entry is None:
            fn, meta = make_eval_fn(self._symbol, is_train)
            entry = (jax.jit(fn), meta)
            self._fn_cache[is_train] = entry
        fn, meta = entry
        input_map = dict(zip(self._input_names, args))
        arg_vals = []
        for name in meta["arg_names"]:
            if name in input_map:
                arg_vals.append(input_map[name]._data)
            else:
                arg_vals.append(self._params[name].data().data_jax)
        aux_vals = [self._params[n].data().data_jax for n in meta["aux_names"]]
        seed = _np.random.randint(0, 2**31 - 1)
        outs, new_aux = fn(arg_vals, aux_vals, seed)
        ctx = args[0]._ctx if args else None
        out_nds = [NDArray(o, ctx) for o in outs]
        for name, v in zip(meta["aux_names"], new_aux):
            with autograd.pause():
                self._params[name].data()._assign(v)
        return out_nds if len(out_nds) > 1 else out_nds[0]
