"""Gluon-level pipeline and expert parallelism.

Framework API over the jax-level schedules in parallel/pp.py (GPipe
ring over the 'pp' mesh axis) and parallel/moe.py (GShard top-2 routing
over 'ep').  The reference has neither (SURVEY.md §2.3: PP/EP absent in
MXNet; its closest capability is manual group2ctx model parallelism,
src/executor/graph_executor.cc:1628) — these make both reachable from
ordinary Gluon models driven by GluonTrainStep, the same way dp/tp are.

    stages = [make_transformer_block() for _ in range(4)]
    for s in stages:
        s.initialize()
        s(probe)                       # resolve deferred shapes
    net = nn.HybridSequential()
    net.add(embed, PipelineBlock(stages), head)
    ...
    step = GluonTrainStep(net, loss, mesh=mesh,
                          param_spec_fn=param_spec_fn_for(net))

    moe = MoE(d_model=64, d_hidden=256, n_experts=8)   # a Gluon Block
    # anywhere in a model; add collect_moe_aux(net) to the task loss
"""

from __future__ import annotations

import numpy as _np

from ... import initializer as _init
from ...ndarray import NDArray, array as _nd_array
from ..block import Block, _StagingScope, update_aux_state
from ..parameter import param_override

__all__ = ["PipelineBlock", "MoE", "collect_moe_aux", "param_spec_fn_for"]


class PipelineBlock(Block):
    """Run a stack of architecturally-identical Gluon stages as a GPipe
    pipeline over the 'pp' mesh axis.

    Construction consumes the (already initialized) per-stage blocks:
    their parameter values are stacked into this block's own Parameters
    with a leading stage axis, which is what makes per-stage placement
    expressible as a sharding (PartitionSpec('pp', ...) on dim 0) —
    separate per-stage arrays cannot be pinned to single mesh ranks.

    Without a mesh (or on a mesh whose 'pp' axis is 1) the block runs
    the stages sequentially — identical math, so models build and debug
    single-device and shard by calling ``attach_mesh``.

    Stages must be shape-homogeneous (activation in == activation out).
    Aux state (BatchNorm running stats) is supported (r4): each stage's
    aux stacks into a grad_req='null' Parameter sharded over 'pp' like
    the weights, and updates accumulate per microbatch (the EMA applies
    once per microbatch a stage actually processes — the semantics of
    training with microbatch-sized batches, the standard GPipe
    BatchNorm contract; fill/drain ticks never touch the stats).
    """

    def __init__(self, stages, n_microbatches=None, axis="pp", **kwargs):
        super().__init__(**kwargs)
        if not stages:
            raise ValueError("PipelineBlock needs at least one stage")
        self._n_stages = len(stages)
        self._axis = axis
        self._n_micro = n_microbatches
        self._gpipe = None
        self._mesh = None
        # held outside __setattr__ registration: the template provides
        # the stage computation; its own params are shadowed by
        # param_override on every call
        self.__dict__["_template"] = stages[0]

        tmpl = stages[0]._collect_params_with_prefix()
        names = sorted(tmpl)
        for s in stages[1:]:
            if sorted(s._collect_params_with_prefix()) != names:
                raise ValueError("pipeline stages must share one "
                                 "parameter structure")
        self.__dict__["_tmpl_params"] = {}
        self._safe_names = []
        self._aux_safe_names = []
        for name in names:
            p0 = tmpl[name]
            if p0._data is None:
                raise ValueError(
                    "stage parameter %s is uninitialized — initialize() "
                    "each stage (and run a probe batch if shapes are "
                    "deferred) before building the PipelineBlock" % name)
            stacked = _np.stack(
                [s._collect_params_with_prefix()[name].data().asnumpy()
                 for s in stages])
            safe = "stage_" + name.replace(".", "__")
            if safe in self._tmpl_params:
                # '__'-escaping is not injective against names that
                # already contain '__'; refuse rather than silently
                # dropping a parameter from the override map
                raise ValueError(
                    "stage parameter names %r collide after mangling; "
                    "rename the layer" % name)
            param = self.params.get(safe, shape=stacked.shape,
                                    dtype=p0.dtype,
                                    grad_req=p0.grad_req)
            setattr(self, safe, param)     # registers in _reg_params
            param.initialize(init=_init.Constant(0))
            param.set_data(_nd_array(stacked))
            self._safe_names.append(safe)
            if p0.grad_req == "null":      # aux state (BN running stats)
                self._aux_safe_names.append(safe)
            self._tmpl_params[safe] = p0

    # -- mesh plumbing

    def attach_mesh(self, mesh, n_microbatches=None):
        """Enable the GPipe schedule on ``mesh`` (its '{axis}' size must
        equal the stage count); pass mesh=None to fall back to
        sequential execution."""
        if mesh is None or mesh.shape.get(self._axis, 1) == 1:
            self._mesh, self._gpipe = None, None
            return self
        if mesh.shape[self._axis] != self._n_stages:
            raise ValueError("mesh %s axis size %d != %d stages"
                             % (self._axis, mesh.shape[self._axis],
                                self._n_stages))
        from ...parallel.pp import GPipe

        self._mesh = mesh
        # remember the effective microbatch count: the sequential
        # fallback must chunk BN stages into the SAME microbatches, or
        # detaching the mesh would change numerics
        if n_microbatches is not None:
            self._n_micro = n_microbatches
        if self._aux_safe_names:
            self._gpipe = GPipe(self._jax_stage_fn_aux, mesh,
                                n_microbatches or self._n_micro,
                                axis=self._axis, has_aux=True)
        else:
            self._gpipe = GPipe(self._jax_stage_fn, mesh,
                                n_microbatches or self._n_micro,
                                axis=self._axis)
        return self

    def param_spec(self, name, shape):
        """PartitionSpec for one of this block's stacked params (dim 0
        over the pp axis), or None for foreign params."""
        from jax.sharding import PartitionSpec as P

        if name in {self._reg_params[s].name for s in self._safe_names}:
            return P(self._axis, *([None] * (len(shape) - 1)))
        return None

    # -- execution

    def _override_for(self, sliced):
        return {self._tmpl_params[s]: v for s, v in sliced.items()}

    def _jax_stage_fn(self, tree, x):
        """One stage applied functionally (runs per-rank inside
        shard_map; tree = this rank's stage slice)."""
        override = {self._tmpl_params[s]: NDArray(v)
                    for s, v in tree.items()}
        scope = _StagingScope()
        with param_override(override), scope:
            y = self._template(NDArray(x))
        if scope.aux_updates:  # unreachable when _aux_safe_names is
            # empty unless a stage mutates aux outside its Parameters
            raise RuntimeError(
                "stage produced aux updates for parameters not owned by "
                "the PipelineBlock — register the aux state as stage "
                "parameters")
        return y._data

    def _jax_stage_fn_aux(self, tree, x, aux_tree):
        """has_aux stage fn: aux_tree is this rank's stage aux slice;
        returns (y, new_aux_tree) with the template's BatchNorm-style
        updates routed back to their stacked slots."""
        override = {self._tmpl_params[s]: NDArray(v)
                    for s, v in tree.items()}
        override.update({self._tmpl_params[s]: NDArray(v)
                         for s, v in aux_tree.items()})
        scope = _StagingScope()
        with param_override(override), scope:
            y = self._template(NDArray(x))
        new_aux = {}
        for s in self._aux_safe_names:
            upd = scope.aux_updates.pop(self._tmpl_params[s], None)
            new_aux[s] = upd if upd is not None else aux_tree[s]
        if scope.aux_updates:
            raise RuntimeError(
                "stage produced aux updates for parameters not owned by "
                "the PipelineBlock — register the aux state as stage "
                "parameters")
        return y._data, new_aux

    def forward(self, x):
        aux_names = set(self._aux_safe_names)
        train_names = [s for s in self._safe_names if s not in aux_names]
        stacked = {s: self._reg_params[s].data() for s in train_names}
        stacked_aux = {s: self._reg_params[s].data()
                       for s in self._aux_safe_names}
        if self._gpipe is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            # place onto the mesh shardings shard_map expects: a no-op
            # when GluonTrainStep already sharded the params over 'pp',
            # and the eager-call migration path otherwise
            def put(tree):
                return {
                    s: jax.device_put(
                        v._data,
                        NamedSharding(self._mesh, P(
                            self._axis, *([None] * (v._data.ndim - 1)))))
                    for s, v in tree.items()}

            tree = put(stacked)
            xj = jax.device_put(x._data, NamedSharding(self._mesh, P()))
            if self._aux_safe_names:
                y, new_aux = self._gpipe(tree, xj, put(stacked_aux))
                for s, v in new_aux.items():
                    update_aux_state(self._reg_params[s], NDArray(v))
                return NDArray(y)
            return NDArray(self._gpipe(tree, xj))
        # sequential fallback: same math as the pipelined schedule.
        # Aux-free stages run the full batch at once.  Aux-bearing
        # stages run per MICROBATCH with the aux chained across chunks
        # — exactly what each GPipe rank computes (per-microbatch BN
        # statistics, one EMA step per microbatch) — so attaching or
        # detaching the mesh never changes numerics.
        import jax.numpy as jnp

        from ... import autograd as _autograd

        aux_set = set(self._aux_safe_names)
        # chunking matters only when BN stats are being UPDATED: eval
        # forwards normalize with the running stats, so microbatching
        # changes nothing and odd inference batches must keep working
        chunk = bool(aux_set) and _autograd.is_training()
        n_micro = (self._n_micro or self._n_stages) if chunk else 1
        if x.shape[0] % n_micro:
            raise ValueError(
                "batch %d not divisible by %d microbatches"
                % (x.shape[0], n_micro))
        new_aux_rows = {s: [] for s in self._aux_safe_names}
        for i in range(self._n_stages):
            override = self._override_for(
                {s: NDArray(v._data[i]) for s, v in stacked.items()})
            aux_i = {s: v._data[i] for s, v in stacked_aux.items()}
            chunks = []
            for m in range(n_micro):
                lo = m * (x.shape[0] // n_micro)
                hi = lo + x.shape[0] // n_micro
                override.update(self._override_for(
                    {s: NDArray(v) for s, v in aux_i.items()}))
                scope = _StagingScope()
                with param_override(override), scope:
                    chunks.append(self._template(x[lo:hi] if n_micro > 1
                                                 else x))
                for s in self._aux_safe_names:
                    upd = scope.aux_updates.pop(self._tmpl_params[s],
                                                None)
                    if upd is not None:
                        aux_i[s] = upd
                if scope.aux_updates:
                    raise RuntimeError(
                        "stage produced aux updates for parameters not "
                        "owned by the PipelineBlock — register the aux "
                        "state as stage parameters")
            x = (chunks[0] if n_micro == 1
                 else NDArray(jnp.concatenate([c._data for c in chunks])))
            for s in self._aux_safe_names:
                new_aux_rows[s].append(aux_i[s])
        for s, rows in new_aux_rows.items():
            update_aux_state(self._reg_params[s], NDArray(jnp.stack(rows)))
        return x


class MoE(Block):
    """Drop-in mixture-of-experts feed-forward Gluon block (GShard top-2
    routing with fixed capacity; parallel/moe.py MoEFFN underneath).

    Input (B, S, d_model) -> output (B, S, d_model).  The expert axis of
    ``wi``/``wo`` shards over the 'ep' mesh axis via ``param_spec``;
    GSPMD inserts the dispatch/combine all-to-alls.  After each forward,
    ``aux_loss`` holds the load-balancing loss — add
    ``collect_moe_aux(net)`` (times a small factor) to the task loss.
    """

    def __init__(self, d_model, d_hidden, n_experts, capacity_factor=1.25,
                 axis="ep", **kwargs):
        super().__init__(**kwargs)
        from ...parallel.moe import MoEFFN

        self.__dict__["_ffn"] = MoEFFN(d_model, d_hidden, n_experts,
                                       capacity_factor=capacity_factor,
                                       axis=axis)
        self._axis = axis
        s1 = (2.0 / (d_model + d_hidden)) ** 0.5
        with self.name_scope():
            # *_weight suffixes route the name-dispatched initializer
            # to its weight filler (initializer.py Initializer.__call__)
            self.gate = self.params.get(
                "gate_weight", shape=(d_model, n_experts),
                init=_init.Normal((1.0 / d_model) ** 0.5))
            self.wi = self.params.get(
                "wi_weight", shape=(n_experts, d_model, d_hidden),
                init=_init.Normal(s1))
            self.wo = self.params.get(
                "wo_weight", shape=(n_experts, d_hidden, d_model),
                init=_init.Normal(s1))
        self._last_aux = None

    def param_spec(self, name, shape):
        from jax.sharding import PartitionSpec as P

        if name == self.wi.name or name == self.wo.name:
            return P(self._axis, *([None] * (len(shape) - 1)))
        if name == self.gate.name:
            return P()
        return None

    @property
    def aux_loss(self):
        """Load-balancing aux loss from the most recent forward.

        Trace-local: read it inside the same staged step the forward
        ran in (that is what ``collect_moe_aux`` does when the loss
        block calls it), or after an eager forward.  Reading it after a
        jitted GluonTrainStep call hands back a dead tracer and jax
        raises its leaked-tracer error on use — log the balancing loss
        by returning it from the loss instead.
        """
        if self._last_aux is None:
            raise RuntimeError("MoE.aux_loss read before any forward")
        return self._last_aux

    def forward(self, x):
        y, aux = self._ffn.apply(
            {"gate": self.gate.data()._data, "wi": self.wi.data()._data,
             "wo": self.wo.data()._data}, x._data)
        self._last_aux = NDArray(aux)
        return NDArray(y)


def collect_moe_aux(block):
    """Sum aux_loss over every MoE in a block tree (call after the
    forward, inside the same autograd/staging scope).  Compat spelling
    of ``Block.collect_aux_losses`` restricted to MoE blocks."""
    total = None
    stack = [block]
    seen = set()  # a shared block reachable twice contributes once
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        if isinstance(b, MoE):
            aux = b.aux_loss
            total = aux if total is None else total + aux
        stack.extend(b._children.values())
    if total is None:
        raise ValueError("no MoE blocks found under %r" % (block,))
    return total


def param_spec_fn_for(net, default=None):
    """Build a GluonTrainStep ``param_spec_fn`` by asking every block in
    the tree that exposes ``param_spec`` (PipelineBlock: 'pp', MoE:
    'ep'); everything else gets ``default`` (replicated)."""
    from jax.sharding import PartitionSpec as P

    providers = []
    stack = [net]
    while stack:
        b = stack.pop()
        if hasattr(b, "param_spec"):
            providers.append(b)
        stack.extend(b._children.values())

    def spec_fn(name, shape):
        for p in providers:
            spec = p.param_spec(name, shape)
            if spec is not None:
                return spec
        return default if default is not None else P()

    return spec_fn
