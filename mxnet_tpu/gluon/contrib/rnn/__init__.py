"""Gluon contrib rnn (reference: python/mxnet/gluon/contrib/rnn/):
convolutional recurrent cells, VariationalDropoutCell, and LSTMPCell
(projected LSTM).
"""

from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell
from .conv_rnn_cell import (Conv1DGRUCell, Conv1DLSTMCell, Conv1DRNNCell,
                            Conv2DGRUCell, Conv2DLSTMCell, Conv2DRNNCell,
                            Conv3DGRUCell, Conv3DLSTMCell, Conv3DRNNCell)
from .rnn_cell import VariationalDropoutCell

__all__ = ["LSTMPCell", "VariationalDropoutCell",
           "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


class LSTMPCell(HybridRecurrentCell):
    """LSTM with projection (reference: gluon/contrib/rnn/rnn_cell.py
    LSTMPCell, from the LSTMP paper)."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slice_gates[0])
        forget_gate = F.sigmoid(slice_gates[1])
        in_transform = F.tanh(slice_gates[2])
        out_gate = F.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.tanh(next_c)
        next_r = F.FullyConnected(hidden, h2r_weight, None, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
