"""Convolutional recurrent cells.

Reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py
(Conv{1,2,3}D{RNN,LSTM,GRU}Cell) — recurrent cells whose input-to-hidden
and hidden-to-hidden transforms are convolutions, keeping spatial
structure in the state.  The h2h convolution must preserve the spatial
shape (odd kernel, stride 1, pad = dilate*(k-1)//2), as the reference
asserts.
"""

from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(val, n, name):
    if isinstance(val, int):
        return (val,) * n
    val = tuple(val)
    assert len(val) == n, "%s must have %d elements" % (name, n)
    return val


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery: i2h/h2h conv parameters + spatial state shape
    inference (reference: conv_rnn_cell.py:37 _BaseConvRNNCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert conv_layout.startswith("NC"), (
            "only channel-first layouts (NCW/NCHW/NCDHW) are supported, "
            "got %r" % (conv_layout,))
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims
        self._i2h_kernel = _tup(i2h_kernel, dims, "i2h_kernel")
        self._h2h_kernel = _tup(h2h_kernel, dims, "h2h_kernel")
        assert all(k % 2 == 1 for k in self._h2h_kernel), (
            "h2h_kernel must be odd to preserve the state shape, got %s"
            % (self._h2h_kernel,))
        self._i2h_pad = _tup(i2h_pad, dims, "i2h_pad")
        self._i2h_dilate = _tup(i2h_dilate, dims, "i2h_dilate")
        self._h2h_dilate = _tup(h2h_dilate, dims, "h2h_dilate")
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        in_c = self._input_shape[0]
        spatial = self._input_shape[1:]
        self._state_shape = (hidden_channels,) + tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad, self._i2h_dilate,
                                  self._i2h_kernel))

        ng = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(ng * hidden_channels, in_c) +
            self._i2h_kernel, init=i2h_weight_initializer,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(ng * hidden_channels, hidden_channels) +
            self._h2h_kernel, init=h2h_weight_initializer,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}] * self._n_states

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel,
                            stride=(1,) * self._dims,
                            pad=self._i2h_pad, dilate=self._i2h_dilate,
                            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel,
                            stride=(1,) * self._dims,
                            pad=self._h2h_pad, dilate=self._h2h_dilate,
                            num_filter=ng * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    _gate_names = ("",)
    _n_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    _gate_names = ("_i", "_f", "_c", "_o")
    _n_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        in_gate, forget_gate, in_trans, out_gate = F.SliceChannel(
            gates, num_outputs=4, axis=1)
        in_gate = F.Activation(in_gate, act_type="sigmoid")
        forget_gate = F.Activation(forget_gate, act_type="sigmoid")
        in_trans = F.Activation(in_trans, act_type=self._activation)
        out_gate = F.Activation(out_gate, act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * F.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _gate_names = ("_r", "_z", "_o")
    _n_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_o = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_o = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        trans = F.Activation(i2h_o + reset * h2h_o,
                             act_type=self._activation)
        out = (1.0 - update) * trans + update * states[0]
        return out, [out]


def _make(cell_base, dims, layout, default_act):
    class Cell(cell_base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=(0,) * dims, i2h_dilate=(1,) * dims,
                     h2h_dilate=(1,) * dims, i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer="zeros",
                     h2h_bias_initializer="zeros", conv_layout=layout,
                     activation=default_act, prefix=None, params=None):
            super().__init__(input_shape=input_shape,
                             hidden_channels=hidden_channels,
                             i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel,
                             i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                             h2h_dilate=h2h_dilate,
                             i2h_weight_initializer=i2h_weight_initializer,
                             h2h_weight_initializer=h2h_weight_initializer,
                             i2h_bias_initializer=i2h_bias_initializer,
                             h2h_bias_initializer=h2h_bias_initializer,
                             dims=dims, conv_layout=conv_layout,
                             activation=activation, prefix=prefix,
                             params=params)

    return Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "NCW", "tanh")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "NCHW", "tanh")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "NCDHW", "tanh")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "NCW", "tanh")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "NCHW", "tanh")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "NCDHW", "tanh")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "NCW", "tanh")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "NCHW", "tanh")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "NCDHW", "tanh")

for _name, _cls in list(globals().items()):
    if _name.startswith("Conv") and _name.endswith("Cell"):
        _cls.__name__ = _name
        _cls.__qualname__ = _name
