"""Contrib recurrent cells (reference: python/mxnet/gluon/contrib/rnn/
rnn_cell.py: VariationalDropoutCell, LSTMPCell)."""

from __future__ import annotations

from ...rnn.rnn_cell import _ModifierCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(_ModifierCell):
    """Variational (same-mask-across-time) dropout on a base cell
    (reference: contrib/rnn/rnn_cell.py:27; Gal & Ghahramani 2016).

    Masks for inputs/states/outputs are drawn on the first step after
    ``reset()`` and reused for the rest of the sequence.
    """

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def hybrid_forward(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(F.ones_like(states[0]),
                                              p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(F.ones_like(inputs),
                                              p=self.drop_inputs)
        if self.drop_states:
            states = list(states)
            # h is always the first state channel
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask

        next_output, next_states = self.base_cell(inputs, states)

        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(F.ones_like(next_output),
                                               p=self.drop_outputs)
        if self.drop_outputs:
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def __repr__(self):
        return "VariationalDropoutCell(%s)" % self.base_cell.name
