"""Gluon contrib nn layers (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py: Concurrent, HybridConcurrent, Identity, SparseEmbedding,
SyncBatchNorm).
"""

from __future__ import annotations

from ...block import Block, HybridBlock
from ...nn import BatchNorm, HybridSequential, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray

        out = [block(x) for block in self._children.values()]
        return ndarray.concatenate(out, axis=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybrid version of Concurrent."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference:
    src/operator/contrib/sync_batch_norm.cc:48).

    TPU-native: inside a pjit/shard_map-sharded step the batch axis is
    global, so plain BatchNorm already computes global-batch statistics
    (stats reductions become XLA psums over the mesh).  This subclass
    exists for API parity; `num_devices` is accepted and unused.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
