"""Gluon contrib nn layers (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py: Concurrent, HybridConcurrent, Identity, SparseEmbedding,
SyncBatchNorm, PixelShuffle1D/2D/3D).
"""

from __future__ import annotations

from ...block import Block, HybridBlock
from ...nn import BatchNorm, HybridSequential, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray

        out = [block(x) for block in self._children.values()]
        return ndarray.concatenate(out, axis=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybrid version of Concurrent."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference:
    src/operator/contrib/sync_batch_norm.cc:48).

    TPU-native: inside a pjit/shard_map-sharded step the batch axis is
    global, so plain BatchNorm already computes global-batch statistics
    (stats reductions become XLA psums over the mesh).  This subclass
    exists for API parity; `num_devices` is accepted and unused.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class SparseEmbedding(Block):
    """Embedding whose gradient is row_sparse — only looked-up rows are
    touched by lazy optimizers (reference: basic_layers.py:118
    SparseEmbedding; meant for very large vocabularies with
    sparse-capable optimizers)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._dtype = dtype
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray

        return ndarray.Embedding(x, self.weight.data(x.context),
                                 input_dim=self._input_dim,
                                 output_dim=self._output_dim,
                                 dtype=self._dtype, sparse_grad=True)

    def __repr__(self):
        return "SparseEmbedding(%d -> %d, %s)" % (
            self._input_dim, self._output_dim, self._dtype)


class PixelShuffle1D(HybridBlock):
    """Upsample (N, C*f, W) -> (N, C, W*f) (reference:
    basic_layers.py:244)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        x = F.reshape(x, (0, -4, -1, f, 0))  # (N, C, f, W)
        x = F.transpose(x, (0, 1, 3, 2))     # (N, C, W, f)
        x = F.reshape(x, (0, 0, -3))         # (N, C, W*f)
        return x

    def __repr__(self):
        return "PixelShuffle1D(%d)" % self._factor


class PixelShuffle2D(HybridBlock):
    """Upsample (N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) (reference:
    basic_layers.py:292)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * 2
        except TypeError:
            self._factors = tuple(int(f) for f in factor)
            assert len(self._factors) == 2

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, (0, -4, -1, f1 * f2, 0, 0))  # (N, C, f1*f2, H, W)
        x = F.reshape(x, (0, 0, -4, f1, f2, 0, 0))    # (N, C, f1, f2, H, W)
        x = F.transpose(x, (0, 1, 4, 2, 5, 3))        # (N, C, H, f1, W, f2)
        x = F.reshape(x, (0, 0, -3, -3))              # (N, C, H*f1, W*f2)
        return x

    def __repr__(self):
        return "PixelShuffle2D(%s)" % (self._factors,)


class PixelShuffle3D(HybridBlock):
    """Upsample (N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)
    (reference: basic_layers.py:354)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * 3
        except TypeError:
            self._factors = tuple(int(f) for f in factor)
            assert len(self._factors) == 3

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        # (N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)
        x = F.reshape(x, (0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.reshape(x, (0, 0, -4, f1, f2 * f3, 0, 0, 0))
        x = F.reshape(x, (0, 0, 0, -4, f2, f3, 0, 0, 0))
        # (N, C, f1, f2, f3, D, H, W) -> (N, C, D, f1, H, f2, W, f3)
        x = F.transpose(x, (0, 1, 5, 2, 6, 3, 7, 4))
        x = F.reshape(x, (0, 0, -3, -3, -3))
        return x

    def __repr__(self):
        return "PixelShuffle3D(%s)" % (self._factors,)
