"""Language-model datasets (reference: python/mxnet/gluon/contrib/data/
text.py WikiText2/WikiText103).

Zero-egress container: the reference downloads the corpora; here the
constructor reads a LOCAL copy (``root/wiki.<segment>.tokens``) with the
same tokenization/EOS/indexing semantics, and raises a clear error
explaining how to provide the file when it is absent.
"""

from __future__ import annotations

import io
import os

import numpy as np

from ....base import MXNetError
from ... import data as _data

__all__ = ["WikiText2", "WikiText103"]

EOS_TOKEN = "<eos>"


class _WikiText(_data.dataset.Dataset):
    _name = "wikitext"

    def __init__(self, root, segment, seq_len):
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = seq_len
        self.vocabulary = None
        self._get_data()

    def _file_path(self):
        return os.path.join(self._root,
                            "wiki.%s.tokens" % self._segment)

    def _get_data(self):
        path = self._file_path()
        if not os.path.exists(path):
            raise MXNetError(
                "%s: %s not found. Downloads are unavailable in this "
                "environment — place the extracted %s corpus file at "
                "that path (same format as the reference's "
                "gluon/dataset/%s archive)."
                % (type(self).__name__, path, self._name, self._name))
        with io.open(path, "r", encoding="utf8") as fin:
            content = fin.read()
        self._build_vocab(content)
        raw_data = [line for line in
                    [x.strip().split() for x in content.splitlines()] if line]
        for line in raw_data:
            line.append(EOS_TOKEN)
        flat = [x for line in raw_data for x in line if x]
        idx = [self._vocab_map[t] for t in flat]
        data, label = np.array(idx[0:-1], np.int32), np.array(idx[1:],
                                                              np.int32)
        n = (len(data) // self._seq_len) * self._seq_len
        from ... import data as gdata  # noqa: F401 (package init ordering)
        from .... import ndarray as nd

        self._data = nd.array(data[:n].reshape(-1, self._seq_len),
                              dtype="int32")
        self._label = nd.array(label[:n].reshape(-1, self._seq_len),
                               dtype="int32")

    def _build_vocab(self, content):
        tokens = sorted(set(content.split()) | {EOS_TOKEN})
        self._vocab_map = {t: i for i, t in enumerate(tokens)}
        try:
            from ....contrib.text import Vocabulary

            self.vocabulary = Vocabulary(
                {t: 1 for t in tokens})
        except Exception:
            self.vocabulary = None

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """WikiText-2 (reference: contrib/data/text.py:105)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-2"),
                 segment="train", seq_len=35):
        self._name = "wikitext-2"
        super().__init__(root, segment, seq_len)


class WikiText103(_WikiText):
    """WikiText-103 (reference: contrib/data/text.py:143)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-103"),
                 segment="train", seq_len=35):
        self._name = "wikitext-103"
        super().__init__(root, segment, seq_len)
