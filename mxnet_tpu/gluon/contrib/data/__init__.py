"""Gluon contrib data (reference: python/mxnet/gluon/contrib/data/):
IntervalSampler + language-model datasets."""

from __future__ import annotations

from .sampler import IntervalSampler  # noqa: F401
from .text import WikiText2, WikiText103  # noqa: F401
