"""Dataset samplers (reference: python/mxnet/gluon/contrib/data/
sampler.py)."""

from __future__ import annotations

from ...data import sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(sampler.Sampler):
    """Samples [0, length) at fixed intervals; with rollover, restarts
    from each skipped offset until all items are visited (reference:
    contrib/data/sampler.py IntervalSampler)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, \
            "Interval %d must be <= length %d" % (interval, length)
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            for j in range(i, self._length, self._interval):
                yield j

    def __len__(self):
        return self._length
