"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""

from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import data  # noqa: F401
from . import parallel  # noqa: F401
