"""Gluon utilities (reference: python/mxnet/gluon/utils.py).

split_and_load is the Gluon data-parallel entry: slice a batch across
contexts.  On TPU the preferred path is a sharded batch over a
jax.sharding Mesh (parallel/), but the per-ctx list API is kept for
parity with the reference multi-device semantics.
"""

from __future__ import annotations

import numpy as _np

from .. import ndarray
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into `num_slice` pieces along batch_axis
    (reference: gluon/utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's a multiple of the number of "
            "devices, or set even_split=False." % (data.shape, num_slice, batch_axis))
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto one context
    (reference: gluon/utils.py split_and_load)."""
    if not isinstance(data, NDArray):
        data = ndarray.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


# one fused clip executable: norm + finite flag + clamped scale + the
# scaled arrays, all in a single XLA computation (reuses the health
# layer's global-norm kernel; jit-cached per shape set, max_norm traced)
_CLIP_KERNEL: list = []


def _clip_kernel():
    if not _CLIP_KERNEL:
        import jax
        import jax.numpy as jnp

        from .. import health as _health

        def _clip(vals, max_norm):
            norm = _health.global_norm(vals)
            finite = jnp.isfinite(norm)
            # a non-finite norm must leave the arrays untouched
            # (reference semantics: the host `if scale < 1.0` branch was
            # False for NaN) — callers detect via the returned norm
            scale = jnp.where(finite,
                              jnp.minimum(jnp.float32(1.0),
                                          max_norm / (norm + 1e-8)),
                              jnp.float32(1.0))
            out = [(v * scale).astype(v.dtype) for v in vals]
            return out, jnp.stack([norm, finite.astype(jnp.float32)])

        _CLIP_KERNEL.append(jax.jit(_clip))
    return _CLIP_KERNEL[0]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the concatenated L2 norm is at most max_norm
    (reference: gluon/utils.py clip_global_norm).

    TPU-native: the norm, the nan/inf check, and the clamped scale are
    ONE fused device computation (the health layer's global-norm
    kernel), and the rescale applies on device unconditionally — no
    host-side ``if scale < 1.0`` branch, so the compute path stays
    host-sync-free.  The only host materialization is the returned
    scalar (the function's contract), fetched once together with the
    fused finite flag."""
    from .. import health as _health

    assert len(arrays) > 0
    scaled, stats = _clip_kernel()([a._data for a in arrays],
                                   _np.float32(max_norm))
    host = _health._fetch([stats])[0]
    total_norm, finite = float(host[0]), bool(host[1])
    if check_isfinite and not finite:
        import warnings

        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    # rebind only when clipping actually happened (scale < 1): the
    # common under-norm step keeps its buffers (no tracker churn), and
    # a non-finite norm leaves the arrays untouched — both the
    # reference's `if scale < 1.0` semantics, decided off the scalar
    # the contract already fetched
    if finite and total_norm > max_norm:
        for a, new in zip(arrays, scaled):
            a._assign(new)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Parity stub: this environment has no network egress; point `path`
    at a pre-downloaded file instead (reference: gluon/utils.py download)."""
    import os

    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        "download(%s) unavailable: no network egress in this environment. "
        "Place the file at %s manually." % (url, fname))
