"""Gluon utilities (reference: python/mxnet/gluon/utils.py).

split_and_load is the Gluon data-parallel entry: slice a batch across
contexts.  On TPU the preferred path is a sharded batch over a
jax.sharding Mesh (parallel/), but the per-ctx list API is kept for
parity with the reference multi-device semantics.
"""

from __future__ import annotations

import numpy as _np

from .. import ndarray
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into `num_slice` pieces along batch_axis
    (reference: gluon/utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's a multiple of the number of "
            "devices, or set even_split=False." % (data.shape, num_slice, batch_axis))
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto one context
    (reference: gluon/utils.py split_and_load)."""
    if not isinstance(data, NDArray):
        data = ndarray.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the concatenated L2 norm is at most max_norm
    (reference: gluon/utils.py clip_global_norm)."""
    assert len(arrays) > 0
    ctx = arrays[0].context
    total = None
    for a in arrays:
        n = (a.astype("float32") ** 2).sum()
        total = n if total is None else total + n.as_in_context(ctx)
    total_norm = float(total.sqrt().asscalar())
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings

        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Parity stub: this environment has no network egress; point `path`
    at a pre-downloaded file instead (reference: gluon/utils.py download)."""
    import os

    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        "download(%s) unavailable: no network egress in this environment. "
        "Place the file at %s manually." % (url, fname))
