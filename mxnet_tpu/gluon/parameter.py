"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (Parameter, ParameterDict,
Constant; deferred initialization via DeferredInitializationError).

TPU-native notes: a Parameter keeps one NDArray per Context (the
reference keeps per-GPU copies managed by the Trainer/KVStore; here
multi-device data parallelism normally rides a jax.sharding Mesh
instead, but the per-ctx list API is preserved for parity).  Gradient
buffers attach through the autograd tape (autograd.mark_variables),
matching the reference's attach_grad semantics.
"""

from __future__ import annotations

import re

import numpy as _np

from .. import autograd, initializer, ndarray
from ..base import MXNetError, np_dtype
from ..context import Context, cpu, current_context

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (ndarray.NDArray,)


class DeferredInitializationError(MXNetError):
    """Parameter used before its shape is known (reference:
    gluon/parameter.py DeferredInitializationError)."""


class Parameter:
    """A trainable weight of a Block.

    Parameters follow the reference semantics: created (possibly with an
    unknown shape containing 0s), `initialize()`d with an Initializer,
    then `.data(ctx)` returns the NDArray and `.grad(ctx)` its gradient
    buffer.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._ctx_list = None   # list[Context]
        self._data = None       # list[NDArray] aligned with _ctx_list
        self._grad = None
        self._deferred_init = ()
        self._trainer = None
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape, self.dtype)

    # ------------------------------------------------------------ grad_req
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError("invalid grad_req %r" % (req,))
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for d in self._data:
                    d._ag_node = None
        elif self._data is not None:
            self._init_grad()

    @property
    def stype(self):
        return self._stype

    # ------------------------------------------------------------ init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Allocate and initialize this parameter on ctx(s)."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self.shape is None or any(s <= 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid shape %s."
                % (self.name, self.shape))
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        explicit = init or self.init
        init = explicit or default_init
        if isinstance(init, str):
            init = initializer.create(init)
        data = _np.zeros(self.shape, dtype=np_dtype(self.dtype))
        init_desc = initializer.InitDesc(self.name, global_init=init)
        if explicit is not None and hasattr(init, "_init_weight"):
            # a parameter-level init wins over name-suffix dispatch —
            # the reference routes this through InitDesc
            # attrs['__init__'] to the init's weight filler, so a PReLU
            # 'alpha' with init=Constant fills even though 'alpha' is
            # no known suffix.  Mixed/Load define only __call__ (they
            # dispatch by name themselves) and take the plain path.
            init._init_weight(init_desc, data)
        else:
            init(init_desc, data)  # fills in place (reference semantics)
        self._data = [ndarray.array(data, ctx=c, dtype=self.dtype)
                      for c in self._ctx_list]
        self._deferred_init = ()
        if self.grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self, shape):
        """Complete deferred init once the shape is known (reference:
        _finish_deferred_init in gluon/parameter.py)."""
        shape = tuple(int(s) for s in shape)
        if self.shape is not None and len(self.shape) == len(shape):
            # merge: keep known dims, fill 0s
            merged = []
            for known, new in zip(self.shape, shape):
                if known > 0 and new > 0 and known != new:
                    raise ValueError(
                        "Deferred-init shape mismatch for %s: %s vs %s"
                        % (self.name, self.shape, shape))
                merged.append(known if known > 0 else new)
            shape = tuple(merged)
        self.shape = shape
        if self._deferred_init:
            init, default_init = self._deferred_init
            self._finish_init(init, default_init)

    def _init_grad(self):
        self._grad = [ndarray.zeros(self.shape, ctx=d.context, dtype=self.dtype)
                      for d in self._data]
        for d, g in zip(self._data, self._grad):
            autograd.mark_variables([d], [g], self.grad_req)

    # ------------------------------------------------------------ access
    def _check_initialized(self, ctx=None):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter %s has not been initialized yet because its shape "
                "is unknown (deferred init). Run a forward pass first or set "
                "the full shape." % self.name)
        raise RuntimeError(
            "Parameter %s has not been initialized. You should initialize "
            "parameters with Block.initialize() before use." % self.name)

    def _ctx_index(self, ctx):
        if ctx is None:
            return 0
        for i, c in enumerate(self._ctx_list):
            if c == ctx:
                return i
        # device_id-insensitive fallback: same device type
        for i, c in enumerate(self._ctx_list):
            if c.device_type == ctx.device_type:
                return i
        raise RuntimeError(
            "Parameter %s was not initialized on context %s (has %s)."
            % (self.name, ctx, self._ctx_list))

    def data(self, ctx=None):
        """The parameter value on ctx (reference: Parameter.data)."""
        ov = _override_get(self)
        if ov is not None:
            return ov
        self._check_initialized(ctx)
        return self._data[self._ctx_index(ctx)]

    def list_data(self):
        self._check_initialized()
        return list(self._data)

    def grad(self, ctx=None):
        self._check_initialized(ctx)
        if self._grad is None:
            raise RuntimeError(
                "Cannot get gradient of Parameter %s because grad_req='null'"
                % self.name)
        return self._grad[self._ctx_index(ctx)]

    def list_grad(self):
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError("grad_req='null' for %s" % self.name)
        return list(self._grad)

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return list(self._ctx_list)
        self._check_initialized()
        return list(self._ctx_list)

    def set_data(self, data):
        """Set value on every context."""
        if self._data is None:
            # allow set before init in the deferred case: fixes shape
            if self._deferred_init:
                self._finish_deferred_init(data.shape)
            else:
                raise RuntimeError("Parameter %s not initialized" % self.name)
        if tuple(data.shape) != tuple(self.shape):
            raise ValueError("shape mismatch for %s: %s vs %s"
                             % (self.name, data.shape, self.shape))
        src = data if isinstance(data, ndarray.NDArray) else ndarray.array(data)
        for d in self._data:
            with autograd.pause():
                d._assign(src.as_in_context(d.context)._data.astype(d.dtype))

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad:
            g._assign(g._data * 0)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            cur = self._data[0]
            self._ctx_list = list(ctx)
            self._data = [cur.as_in_context(c) for c in ctx]
            if self.grad_req != "null":
                self._init_grad()
        elif self._deferred_init:
            self._ctx_list = list(ctx)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = [d.astype(dtype) for d in self._data]
            if self._grad is not None:
                self._init_grad()

    def var(self):
        """Symbol variable for this parameter (symbolic composition)."""
        from .. import symbol
        return symbol.Variable(self.name, shape=self.shape, dtype=self.dtype,
                               lr_mult=self.lr_mult, wd_mult=self.wd_mult)

    def __reduce__(self):  # pickling support for DataLoader workers
        return (_rebuild_parameter,
                (self.name, self.grad_req, self.shape, self.dtype,
                 self.lr_mult, self.wd_mult,
                 None if self._data is None else self._data[0].asnumpy()))


def _rebuild_parameter(name, grad_req, shape, dtype, lr_mult, wd_mult, value):
    p = Parameter(name, grad_req=grad_req, shape=shape, dtype=dtype,
                  lr_mult=lr_mult, wd_mult=wd_mult)
    if value is not None:
        p.initialize(init=initializer.Constant(0), ctx=cpu())
        p.set_data(ndarray.array(value))
    return p


class Constant(Parameter):
    """A constant (non-trainable) parameter holding a fixed value
    (reference: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, _np.ndarray):
            value = value.asnumpy() if isinstance(value, ndarray.NDArray) \
                else _np.asarray(value, dtype="float32")
        self.value = value

        class _CInit(initializer.Initializer):
            def _init_weight(s, _, arr):
                arr[...] = value

            _init_default = _init_weight

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


# --------------------------------------------------------------- override
# Thread-local map Parameter -> NDArray(tracer) active while a HybridBlock
# is being staged into one XLA graph (block.py CachedGraph); lets the same
# layer code run both eagerly and under trace.
import threading as _threading

_OVERRIDE = _threading.local()


class param_override:
    def __init__(self, mapping):
        self.mapping = mapping

    def __enter__(self):
        stack = getattr(_OVERRIDE, "stack", None)
        if stack is None:
            stack = _OVERRIDE.stack = []
        stack.append(self.mapping)
        return self

    def __exit__(self, *a):
        _OVERRIDE.stack.pop()


def _override_get(param):
    stack = getattr(_OVERRIDE, "stack", None)
    if not stack:
        return None
    for m in reversed(stack):
        if param in m:
            return m[param]
    return None


class ParameterDict:
    """A prefix-scoped dictionary of Parameters (reference:
    gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        s = "\n".join("  %r" % p for p in self._params.values())
        return "ParameterDict(prefix=%r\n%s\n)" % (self._prefix, s)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Retrieve-or-create a Parameter named prefix+name."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and param.shape is not None and v is not None:
                    v = tuple(v)
                    if len(v) == len(param.shape):
                        merged = tuple(a if a > 0 else b
                                       for a, b in zip(param.shape, v))
                        param.shape = merged
                        continue
                if getattr(param, k, None) in (None, v) or k in ("init",):
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise ValueError("No constant named %s" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("Cannot update self with other because they "
                                 "have different Parameters with the same name %s" % k)
            self._params[k] = v

    # ------------------------------------------------------------ bulk ops
    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for p in self.values():
            p.initialize(None, ctx, default_init=init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def list_ctx(self):
        ctxs = []
        for p in self.values():
            for c in p.list_ctx():
                if c not in ctxs:
                    ctxs.append(c)
        return ctxs

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def cast(self, dtype):
        for p in self.values():
            p.cast(dtype)

    # ------------------------------------------------------------ save/load
    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for p in self.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = p.data().as_in_context(cpu())
        ndarray.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = ndarray.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise IOError("Parameter %s is missing in file %s"
                                  % (name, filename))
        for name, value in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError("Parameter %s loaded from %s is not present "
                                  "in this ParameterDict" % (name, filename))
                continue
            p = self._params[name]
            if p._data is None:
                p.shape = tuple(value.shape)
                p.initialize(init=initializer.Constant(0),
                             ctx=p._ctx_list or ctx or [current_context()])
            p.set_data(value)
