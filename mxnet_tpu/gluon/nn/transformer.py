"""Transformer layers and language model (Gluon HybridBlocks).

The reference ships transformer helper ops (src/operator/contrib/
transformer.cc) and example models built from raw symbols; here the
transformer family is first-class, built TPU-first:

- attention goes through the fused flash-attention op
  (ops/attention.py — Pallas kernel on TPU, XLA-fused fallback off-TPU);
- the layer stack is scan/jit friendly (static shapes, no Python
  control flow on traced values);
- parameter names follow patterns that ``parallel.tp`` partition rules
  match for tensor/sequence-parallel sharding over a device mesh.
"""

from __future__ import annotations

import math

import numpy as _np

from ..block import HybridBlock
from .basic_layers import Dense, Dropout, Embedding, HybridSequential, LayerNorm

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "TransformerEncoder", "TransformerLM"]


class MultiHeadAttention(HybridBlock):
    """Fused self-attention: one packed QKV projection, flash attention,
    output projection.

    Dropout is applied to the projected output (the fused kernel does
    not materialise attention probabilities to drop — the standard
    flash-attention trade-off).
    """

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 use_bias=True, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units %d not divisible by num_heads %d"
                             % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, use_bias=use_bias,
                             prefix="qkv_")
            self.proj = Dense(units, flatten=False, use_bias=use_bias,
                              in_units=units, prefix="proj_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        h, u = self._num_heads, self._units
        d = u // h
        qkv = self.qkv(x)                                 # (B, S, 3U)
        qkv = F.reshape(qkv, shape=(0, 0, 3 * h, d))
        qkv = F.transpose(qkv, axes=(0, 2, 1, 3))          # (B, 3H, S, d)
        q = F.slice_axis(qkv, axis=1, begin=0, end=h)
        k = F.slice_axis(qkv, axis=1, begin=h, end=2 * h)
        v = F.slice_axis(qkv, axis=1, begin=2 * h, end=3 * h)
        o = F.contrib.flash_attention(q, k, v, causal=self._causal)
        o = F.transpose(o, axes=(0, 2, 1, 3))              # (B, S, H, d)
        o = F.reshape(o, shape=(0, 0, u))
        o = self.proj(o)
        return self.drop(o) if self.drop is not None else o


class PositionwiseFFN(HybridBlock):
    """Two-layer MLP; ffn1 is column-parallel, ffn2 row-parallel under
    the tp partition rules."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.act = activation
            self.ffn2 = Dense(units, flatten=False, in_units=hidden_size,
                              prefix="ffn2_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn1(x)
        out = F.LeakyReLU(out, act_type="gelu") if self.act == "gelu" \
            else F.Activation(out, act_type=self.act)
        out = self.ffn2(out)
        return self.drop(out) if self.drop is not None else out


class TransformerEncoderCell(HybridBlock):
    """Pre-LN transformer layer: x + MHA(LN(x)); x + FFN(LN(x))."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 causal=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(prefix="ln1_")
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout,
                                           causal=causal, prefix="attn_")
            self.ln2 = LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       prefix="ffn_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        return x + self.ffn(self.ln2(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, causal=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(TransformerEncoderCell(
                        units, hidden_size, num_heads, dropout=dropout,
                        causal=causal))

    def hybrid_forward(self, F, x):
        return self.layers(x)


class TransformerLM(HybridBlock):
    """Decoder-only (causal) transformer language model.

    Input: (batch, seq) int32 token ids → logits (batch, seq, vocab).
    The flagship long-context model: with a mesh carrying 'sp'/'tp'
    axes and ``parallel.tp.transformer_rules`` shardings, the same
    block trains data-, tensor- and sequence-parallel unchanged.
    """

    def __init__(self, vocab_size, units=512, num_layers=4, num_heads=8,
                 hidden_size=None, max_length=2048, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        hidden_size = hidden_size or 4 * units
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.embed = Embedding(vocab_size, units, prefix="embed_")
            self.pos_embed = Embedding(max_length, units, prefix="pos_")
            self.drop = Dropout(dropout) if dropout else None
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout=dropout,
                causal=True, prefix="enc_")
            self.ln_f = LayerNorm(prefix="lnf_")
            self.logits = Dense(vocab_size, flatten=False, in_units=units,
                                use_bias=False, prefix="logits_")

    def hybrid_forward(self, F, x):
        # token + learned positional embeddings
        emb = self.embed(x) * math.sqrt(self._units)
        pos = F.arange_like(F.slice_axis(x, axis=0, begin=0, end=1), axis=1)
        emb = emb + self.pos_embed(pos)
        if self.drop is not None:
            emb = self.drop(emb)
        out = self.encoder(emb)
        return self.logits(self.ln_f(out))
