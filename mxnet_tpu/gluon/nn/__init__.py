"""Gluon neural-network layers (reference: python/mxnet/gluon/nn/)."""

from .basic_layers import *  # noqa: F401,F403
from .basic_layers import Activation  # noqa: F401
from .conv_layers import *  # noqa: F401,F403
from .activations import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
