"""Basic Gluon neural-network layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (Sequential, Dense,
Dropout, BatchNorm, Embedding, Flatten, Lambda, ...).
"""

from __future__ import annotations

import numpy as _np

from ... import autograd, initializer, ndarray
from ..block import Block, HybridBlock, update_aux_state

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "Embedding", "Flatten", "Lambda",
           "HybridLambda"]


class Sequential(Block):
    """Stack of Blocks run sequentially (reference: basic_layers.py)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, stagable into one XLA graph."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(dot(x, W.T) + b)
    (reference: basic_layers.py Dense; op fully_connected.cc:239)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(units,), init=bias_initializer,
                dtype=dtype, allow_deferred_init=True) if use_bias else None
        self.act = Activation(activation, prefix=activation + "_") \
            if activation is not None else None

    def infer_shape(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense(%s -> %s, %s)" % (
            shape[1] if shape and shape[1] else None, shape[0],
            self.act if self.act else "linear")


class Activation(HybridBlock):
    """Activation layer (relu/sigmoid/tanh/softrelu/softsign)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class Dropout(HybridBlock):
    """Dropout (reference: basic_layers.py Dropout; src/operator/nn/
    dropout.cc — active only in train mode, random path keyed via
    TraceRNG under hybridize)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return "Dropout(p = %s, axes=%s)" % (self._rate, self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with running-stat aux states.

    Reference: basic_layers.py BatchNorm over src/operator/nn/
    batch_norm.cc.  The functional BatchNorm op returns batch stats;
    this layer folds them into the running stats through the
    update_aux_state channel (eager write, or traced side-output when
    staged — block.py _StagingScope)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if _np.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"  # stats kept fp32 (reference behaviour)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        train_stats = autograd.is_training() and not self._use_global_stats
        if train_stats:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                eps=self._epsilon, momentum=self._momentum,
                fix_gamma=not self._scale, use_global_stats=False,
                output_mean_var=True, axis=self._axis)
            m = self._momentum
            update_aux_state(self.running_mean,
                             running_mean * m + mean * (1 - m))
            update_aux_state(self.running_var,
                             running_var * m + var * (1 - m))
            return out
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, use_global_stats=True,
            axis=self._axis)

    def __repr__(self):
        return "BatchNorm(axis=%s, momentum=%s, eps=%s, in_channels=%s)" % (
            self._axis, self._momentum, self._epsilon,
            self.gamma.shape[0] if self.gamma.shape else None)


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: basic_layers.py InstanceNorm)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Layer normalization (reference: basic_layers.py LayerNorm over
    src/operator/nn/layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Index -> dense vector lookup (reference: basic_layers.py Embedding
    over src/operator/tensor/indexing_op.cc)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._dtype = dtype
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim, dtype=self._dtype)

    def __repr__(self):
        return "Embedding(%s -> %s, %s)" % (self._input_dim, self._output_dim,
                                            self._dtype)


class Flatten(HybridBlock):
    """Collapse all dims but batch (reference: basic_layers.py Flatten)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (reference: basic_layers.py Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(ndarray, function):
                raise RuntimeError("Function %s is not found in ndarray" % function)
            self._func_impl = getattr(ndarray, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError("function must be a str or callable")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "Lambda(%s)" % self._func_name


class HybridLambda(HybridBlock):
    """Wrap an F-generic function as a HybridBlock."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function

            def impl(F, *args):
                return getattr(F, function)(*args)

            self._func_impl = impl
        elif callable(function):
            self._func_impl = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError("function must be a str or callable")

    def hybrid_forward(self, F, *args):
        return self._func_impl(F, *args)

    def __repr__(self):
        return "HybridLambda(%s)" % self._func_name
