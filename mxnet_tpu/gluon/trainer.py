"""Gluon Trainer — applies an Optimizer to a set of Parameters.

Reference: python/mxnet/gluon/trainer.py:27 (kvstore setup :169,
step :302, allreduce_grads :331, update :363).

TPU-native notes: on a single chip the update is a direct fused
optimizer-op call per parameter (the reference's updater path).  For
multi-device data parallel, grads living on different devices are
reduced through the KVStore façade ('local'/'device'/'tpu'), whose
'tpu' backend lowers push+pull to an XLA psum over the mesh
(SURVEY.md §2.3) — the sharded flagship path instead jits the whole
train step over the mesh (parallel/data_parallel.py).
"""

from __future__ import annotations

from .. import autopilot as _autopilot
from .. import checkpoint as _ckpt
from .. import device_memory as _dm
from .. import health as _health
from .. import histogram as _histogram
from .. import kvstore as _kvstore
from .. import metrics_timeline as _metrics
from .. import optimizer as _optimizer
from .. import profiler as _profiler
from .. import runtime_stats as _rts
from .. import stepstats as _stepstats
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class _StepTelemetry:
    """THE shared per-step instrumentation of ``Trainer.step`` and
    ``compiled_step.CompiledStep.step``: the ``trainer:step`` span +
    step-wall histogram around the body, the health flight dump when an
    exception unwinds the step, and the accreting end-of-step hook tail
    (device-memory counter event, health step clock, auto-checkpoint,
    stepstats window close, metrics-timeline sample).  One place to
    extend when the next observability layer lands — a hook added here
    fires on BOTH training paths.

    ``compiled=True`` tags the span and pins the auto-checkpoint
    capture (the compiled path donates the param/optimizer buffers on
    its next call — ``checkpoint.save_trainer``'s pin contract)."""

    def __init__(self, trainer, batch_size, hm, compiled=False):
        self.trainer = trainer
        self.batch_size = batch_size
        self.hm = hm
        self.compiled = compiled

    def __enter__(self):
        self._hist_on = _histogram._state["on"]
        if self._hist_on:
            self._t0 = _profiler._now_us()
        args = None
        if _profiler._state["running"]:
            args = {"batch_size": self.batch_size}
            if self.compiled:
                args["compiled"] = 1
        self._span = _profiler.span("trainer:step", "trainer", args=args)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._span.__exit__(exc_type, exc, tb)
        if exc_type is not None:
            if self.hm is not None:
                # the ring holds the steps leading up to the crash —
                # dump it before the exception unwinds the training loop
                self.hm.dump_on_crash()
            return False
        if self._hist_on:
            # step wall-time distribution (guard-first): the per-rank
            # series the cluster report compares for step-time skew
            _histogram.observe("trainer:step",
                               (_profiler._now_us() - self._t0) / 1e6)
        if _dm._state["on"]:
            # per-step live/peak-bytes counter event: anchors the trace's
            # memory timeline even when no buffer was (de)allocated
            _dm.emit_counter()
        if self.hm is not None:
            self.hm.end_step()
        # auto-checkpoint hook (checkpoint.enable()/MXNET_TPU_CKPT):
        # advances the manager's step clock and snapshots at interval
        # boundaries without blocking.  Disabled: one dict read.
        if _ckpt._state["on"]:
            _ckpt.on_step(self.trainer, pin=self.compiled)
        # step-anatomy boundary (stepstats.py): closes the window that
        # opened at the previous step's end, so the recorded wall time
        # covers the whole iteration.  Disabled: one dict read.
        if _stepstats._state["on"]:
            _stepstats.end_step()
        # live metrics timeline: one per-step sample AFTER end_step so
        # the sample carries this step's phase window.  Disabled: one
        # dict read.
        if _metrics._state["on"]:
            _metrics.on_step(self.batch_size)
        # observability autopilot: gated reflexes over the live ring,
        # AFTER the timeline sample so the evidence includes this step.
        # Disabled: one dict read.  An ARMED halt-after-checkpoint
        # reflex raises AutopilotHalt through here by design.
        if _autopilot._state["on"]:
            _autopilot.on_step(self.trainer)
        return False


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("params must be a list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise ValueError("invalid parameter %r" % (p,))
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False

    def _check_contexts(self):
        contexts = None
        for p in self._params:
            ctx = p.list_ctx() if p._data is not None or p._deferred_init else None
            if ctx is None:
                continue
            if contexts is None:
                contexts = ctx
            elif contexts != ctx:
                raise ValueError(
                    "All Parameters must be initialized on the same set of "
                    "contexts, but %s has %s vs %s" % (p.name, ctx, contexts))
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, _optimizer.Optimizer):
            if optimizer_params:
                raise ValueError(
                    "optimizer_params must be empty when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = _optimizer.create(optimizer, **optimizer_params)
            self._optimizer.param_dict = param_dict
        self._updaters = [_optimizer.get_updater(self._optimizer)
                          for _ in self._contexts] or \
                         [_optimizer.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """reference: trainer.py _init_kvstore — dist stores are used even
        with one local context (the other replicas are other processes);
        update_on_kvstore routes the optimizer server-side."""
        kv = None
        if self._kvstore_type:
            kv = _kvstore.create(self._kvstore_type) \
                if isinstance(self._kvstore_type, str) else self._kvstore_type
        # a dist store synchronizes across PROCESSES, so one local
        # context is the normal layout; local stores only matter with
        # multiple local contexts
        if kv is not None and "dist" not in kv.type and \
                len(self._contexts) <= 1:
            kv = None
        if kv is not None:
            if "async" in kv.type and self._update_on_kvstore is False:
                # reference trainer.py raises the same way: async pushes
                # are applied by the server optimizer, so worker-side
                # updates are not expressible
                raise ValueError(
                    "Please set update_on_kvstore=True when training "
                    "with dist_async; updates must run on the kvstore "
                    "servers")
            if self._update_on_kvstore is None:
                # async PS REQUIRES server-side updates; sync dist and
                # local reduce default to worker-side updates
                self._update_on_kvstore = "async" in kv.type
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    kv.init(i, p.data(self._contexts[0]))
            self._kvstore = kv
        else:
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    # ------------------------------------------------------- compiled step
    def compile(self, block, loss, zero=None, mesh=None):
        """Fuse ``block``'s forward + ``loss`` + backward + this
        trainer's optimizer update into ONE donated XLA program
        (``compiled_step.CompiledStep``): ``cs = trainer.compile(net,
        loss_fn)`` then ``cs.step(x, y)`` replaces the whole
        ``record()/backward()/step()`` iteration.  The eager path stays
        the default/debug mode; see docs/COMPILED_STEP.md for the
        donation/rebind contract and the supported-optimizer set.

        ``zero=True`` (default from ``MXNET_TPU_ZERO=1``) builds the
        same fused program with ZeRO weight-update sharding over the
        'dp' mesh axis — params and optimizer state live as 1/n
        per-device shards inside the program
        (``compiled_step.ZeroCompiledStep``, docs/ZERO.md); ``mesh``
        optionally pins the device mesh for that path."""
        from .. import compiled_step as _compiled

        return _compiled.compile_step(block, loss, self, zero=zero,
                                      mesh=mesh)

    # ------------------------------------------------------------ step
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads across devices, then update
        (reference: trainer.py step:302).

        With the numerics health layer enabled (``health.enable()`` /
        ``MXNET_TPU_HEALTH=1``) each sampled step additionally feeds the
        global monitor a fused device-side global grad-norm, per-grad
        NaN/Inf sentinels, and per-param update-to-weight ratios, then
        advances its clock (drain + flight record happen at interval
        boundaries); an unhandled exception dumps the flight recorder
        before propagating.  Disabled: one dict read."""
        _rts.inc("trainer_steps")
        hm = _health.monitor() if _health._state["on"] else None
        with _StepTelemetry(self, batch_size, hm):
            self._step(batch_size, ignore_stale_grad, hm)

    def _health_grads_and_prev(self, hm):
        """Feed gradients to the health monitor and snapshot the
        pre-update weight buffers (device references only — no copies,
        no syncs).  Returns the snapshot for ``_health_updates``."""
        if hm is None or not hm.sampling:
            return None
        named = [(p.name, p.list_grad()[0]) for p in self._params
                 if p.grad_req != "null"]
        hm.observe_grads(named)
        return [(p, p.list_data()[0]._data) for p in self._params
                if p.grad_req != "null"]

    def _health_updates(self, hm, prev):
        """Feed per-param update-to-weight ratios from the pre/post
        update buffer pairs captured by ``_health_grads_and_prev``."""
        if prev is None:
            return
        for p, old in prev:
            hm.observe_update(p.name, p.list_data()[0]._data, old)

    def _step(self, batch_size, ignore_stale_grad, hm=None):
        # rescale BEFORE the kvstore ships the optimizer server-side
        # (reference: step() calls _check_and_rescale_grad first; changing
        # batch_size after init would silently use the stale rescale)
        new_rescale = self._scale / batch_size
        if self._kv_initialized and self._update_on_kvstore and \
                new_rescale != self._optimizer.rescale_grad:
            import warnings

            warnings.warn("batch_size change detected after kvstore "
                          "init; server-side optimizer keeps the "
                          "original rescale_grad")
        self._optimizer.rescale_grad = new_rescale
        if not self._kv_initialized:
            self._contexts = self._contexts or self._check_contexts()
            self._init_kvstore()
        if self._update_on_kvstore:
            # server-side update: push grads, pull back fresh WEIGHTS
            # (reference: trainer.py _update with update_on_kvstore).
            # Health caveat: the aggregated gradient only ever exists on
            # the server, so grad_norm/grad:* here reflect THIS worker's
            # local pre-aggregation grads (the update-to-weight ratios
            # below do reflect the applied server update).
            prev = self._health_grads_and_prev(hm)
            for i, p in enumerate(self._params):
                if p.grad_req == "null":
                    continue
                self._kvstore.push(i, p.list_grad())
                self._kvstore.pull(i, out=p.list_data())
            self._health_updates(hm, prev)
            return
        self._allreduce_grads()
        prev = self._health_grads_and_prev(hm)
        self._update(ignore_stale_grad)
        self._health_updates(hm, prev)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            # reference: trainer.py raises — with a server-side optimizer
            # a push already UPDATES, so the two-phase workflow would pull
            # weights into gradient buffers and corrupt training
            raise ValueError(
                "allreduce_grads() is not supported when updates run on "
                "the kvstore (update_on_kvstore=True); use step() or pass "
                "update_on_kvstore=False")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        with _profiler.span("trainer:allreduce", "trainer"):
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    grads = p.list_grad()
                    self._kvstore.push(i, grads)
                    self._kvstore.pull(i, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise ValueError(
                "update() is not supported when updates run on the "
                "kvstore (update_on_kvstore=True); use step() or pass "
                "update_on_kvstore=False")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        # optimizer_update is a container phase: warm dispatch of the
        # fused optimizer ops inside stays in dispatch_warm; this
        # records the update's exclusive remainder (stepstats.py)
        ss_on = _stepstats._state["on"]
        if ss_on:
            ss_tok = _stepstats.begin()
        with _profiler.span("trainer:update", "trainer"):
            self._update_impl(ignore_stale_grad)
        if ss_on:
            _stepstats.end("optimizer_update", ss_tok)

    def _update_impl(self, ignore_stale_grad=False):
        n_dev = max(len(p.list_data()) for p in self._params) \
            if self._params else 1
        while len(self._updaters) < n_dev:
            # one Updater per device copy: per-index optimizer state must
            # not be shared across copies (reference: trainer.py _updaters)
            self._updaters.append(_optimizer.get_updater(self._optimizer))
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            for upd, data, grad in zip(self._updaters,
                                       p.list_data(), p.list_grad()):
                if getattr(p, "_grad_stype", "default") == "row_sparse" \
                        and getattr(self._optimizer, "lazy_update", False):
                    # sparse_grad param (e.g. Embedding): wrap the dense
                    # autograd result as row_sparse (device-side nonzero-row
                    # scan) so the optimizer's lazy kernel touches only the
                    # used rows; skipped for optimizers w/o lazy kernels
                    from ..ndarray import sparse as _sp
                    grad = _sp.cast_storage(grad, "row_sparse")
                upd(i, grad, data)

    # ------------------------------------------------------------ states
    def save_states(self, fname):
        """Save optimizer/updater state (reference: trainer.py
        save_states) — atomically (temp + fsync + rename via
        ``checkpoint.atomic_write``) and with a version header, so a
        crash mid-save can never leave a torn states file under the
        final name (docs/CHECKPOINTING.md)."""
        import pickle

        payload = self._updaters[0].get_states(dump_optimizer=True) \
            if hasattr(self._updaters[0], "get_states") \
            else self._updaters[0].states
        if not isinstance(payload, bytes):
            payload = pickle.dumps(payload,
                                   protocol=pickle.HIGHEST_PROTOCOL)
        with _ckpt.atomic_write(fname) as tmp:
            with open(tmp, "wb") as f:
                f.write(_ckpt.TRAINER_STATES_MAGIC)
                f.write(bytes([_ckpt.TRAINER_STATES_VERSION]))
                f.write(b"\n")
                f.write(payload)

    def load_states(self, fname):
        """Load optimizer/updater state; understands both the versioned
        header format and legacy headerless pickles."""
        import pickle

        with open(fname, "rb") as f:
            head = f.read(len(_ckpt.TRAINER_STATES_MAGIC))
            if head == _ckpt.TRAINER_STATES_MAGIC:
                version = f.read(1)[0]
                if version > _ckpt.TRAINER_STATES_VERSION:
                    raise ValueError(
                        "trainer states file %s has version %d; this "
                        "build understands <= %d"
                        % (fname, version, _ckpt.TRAINER_STATES_VERSION))
                f.read(1)  # newline
                states = f.read()
            else:
                states = pickle.loads(head + f.read())
        for u in self._updaters:
            if hasattr(u, "set_states"):
                u.set_states(states)
            else:
                u.states = pickle.loads(states) \
                    if isinstance(states, bytes) else states
