"""mx.monitor — per-op output statistics hook.

Reference: python/mxnet/monitor.py (Monitor over
MXExecutorSetMonitorCallback).  Here the hook taps Gluon block forward
hooks / executor outputs instead of engine callbacks.

TPU-native default (PR 5): with no ``stat_func`` the Monitor computes
its statistic **on device** through the numerics health layer's fused
stat kernel (``health.stat_kernel``) and queues the tiny result without
blocking; the host materializes everything in one batch at ``toc()`` —
so monitoring no longer stalls the forward pass on a device->host copy
per watched tensor.  Passing an explicit ``stat_func`` keeps the
reference's host-numpy semantics (a DELIBERATE host-sync point, timed
into ``runtime_stats`` so traces show what it costs the step).
"""

from __future__ import annotations

import re
import time

from . import health as _health
from . import profiler as _profiler
from . import runtime_stats as _rts
from .ndarray import NDArray

__all__ = ["Monitor"]

# device-mode statistic: abs-mean, the reference default (toc() returns
# one value per tensor; NaN/Inf sentinels are the health layer's job)
_DEVICE_STATS = ("abs_mean",)


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        # stat_func=None selects the device-resident path; an explicit
        # stat_func is the legacy host-numpy mode (reference parity)
        self.legacy = stat_func is not None
        self.stat_func = stat_func
        self.interval = interval
        self.step = 0
        self.activated = False
        self.queue = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self._installed = []
        self._kernel = None if self.legacy \
            else _health.stat_kernel(_DEVICE_STATS)

    def install(self, block):
        """Attach to a Gluon block tree (TPU-native analog of
        executor monitor callbacks)."""
        from .gluon.block import is_staging

        def make_hook(name):
            def hook(blk, inputs, outputs):
                if not self.activated or is_staging():
                    return
                outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
                for i, o in enumerate(outs):
                    key = "%s_output%d" % (name, i)
                    if self.re_pattern.match(key) and isinstance(o, NDArray):
                        self._observe(key, o)
            return hook

        def attach(blk, path):
            h = blk.register_forward_hook(make_hook(path or blk.name))
            self._installed.append((blk, h))
            for k, c in blk._children.items():
                attach(c, (path + "." if path else "") + k)

        attach(block, "")
        return self

    def _observe(self, key, o):
        t0 = time.perf_counter()
        if self.legacy:
            # legacy mode is a DELIBERATE host-sync point: the stat is
            # computed on host numpy, blocking on the device value
            # mid-forward (reference semantics).
            with _profiler.span("monitor:stat", "monitor",
                                args={"key": key}):
                value = self.stat_func(o.asnumpy())  # mxlint: disable=trace-host-sync
        else:
            # device mode: queue the fused stat vector, no blocking —
            # inside a staged/hybridized trace the output is a tracer
            # and must not escape, so it is skipped
            if not _health._concrete(o._data):
                return
            with _profiler.span("monitor:stat", "monitor",
                                args={"key": key}
                                if _profiler._state["running"] else None):
                value = self._kernel(o._data)
        _rts.inc("monitor_stats")
        _rts.inc("monitor_seconds", time.perf_counter() - t0)
        self.queue.append((self.step, key, value))

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self):
        """Drain: in device mode every queued stat vector materializes
        here in one batch (the rate-limited sync point); legacy entries
        are already host values."""
        if not self.activated:
            return []
        self.activated = False
        queued = list(self.queue)
        self.queue = []
        if self.legacy:
            res = queued
        else:
            t0 = time.perf_counter()
            host = _health._fetch([v for _, _, v in queued])
            res = [(step, key, vec[0]) for (step, key, _), vec
                   in zip(queued, host)]
            _rts.inc("monitor_seconds", time.perf_counter() - t0)
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            print("Batch: %7d %30s %s" % (step, name, value))
