"""mx.monitor — per-op output statistics hook.

Reference: python/mxnet/monitor.py (Monitor over
MXExecutorSetMonitorCallback).  Here the hook taps Gluon block forward
hooks / executor outputs instead of engine callbacks.
"""

from __future__ import annotations

import re
import time

import numpy as _np

from . import profiler as _profiler
from . import runtime_stats as _rts
from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return _np.abs(x).mean()
        self.stat_func = stat_func
        self.interval = interval
        self.step = 0
        self.activated = False
        self.queue = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self._installed = []

    def install(self, block):
        """Attach to a Gluon block tree (TPU-native analog of
        executor monitor callbacks)."""

        def make_hook(name):
            def hook(blk, inputs, outputs):
                if not self.activated:
                    return
                outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
                for i, o in enumerate(outs):
                    key = "%s_output%d" % (name, i)
                    if self.re_pattern.match(key) and isinstance(o, NDArray):
                        # Monitor is a DELIBERATE host-sync point: the
                        # stat is computed on host numpy, blocking on the
                        # device value mid-forward (reference semantics).
                        # Timed into runtime_stats so traces show what
                        # the monitor costs the step.
                        t0 = time.perf_counter()
                        with _profiler.span("monitor:stat", "monitor",
                                            args={"key": key}):
                            value = self.stat_func(o.asnumpy())  # mxlint: disable=trace-host-sync
                        _rts.inc("monitor_stats")
                        _rts.inc("monitor_seconds",
                                 time.perf_counter() - t0)
                        self.queue.append((self.step, key, value))
            return hook

        def attach(blk, path):
            h = blk.register_forward_hook(make_hook(path or blk.name))
            self._installed.append((blk, h))
            for k, c in blk._children.items():
                attach(c, (path + "." if path else "") + k)

        attach(block, "")
        return self

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = list(self.queue)
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            print("Batch: %7d %30s %s" % (step, name, value))
