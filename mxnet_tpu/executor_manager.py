"""Helpers for multi-device executor management.

Reference: python/mxnet/executor_manager.py (_split_input_slice,
DataParallelExecutorManager used by the legacy FeedForward API).
"""

from __future__ import annotations

import numpy as _np

__all__ = ["_split_input_slice"]


def _split_input_slice(batch_size, work_load_list):
    """Slice a batch according to per-device workloads
    (reference: executor_manager.py:_split_input_slice)."""
    total = sum(work_load_list)
    if total == 0:
        raise ValueError("Invalid workload")
    batch_num_list = [round(batch_size * (float(w) / total))
                      for w in work_load_list]
    batch_num_sum = sum(batch_num_list)
    if batch_num_sum < batch_size:
        batch_num_list[-1] += batch_size - batch_num_sum
    slices = []
    end = 0
    for batch_num in batch_num_list:
        begin = int(min(end, batch_size))
        end = int(min(begin + batch_num, batch_size))
        if begin >= end:
            raise ValueError("Too many slices. Some splits are empty.")
        slices.append(slice(begin, end))
    return slices
