"""``mx.sym.random`` (reference: python/mxnet/symbol/random.py)."""

from .symbol import _create


def uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", **kwargs):
    kwargs.pop("ctx", None)
    return _create("_random_uniform", [], {"low": low, "high": high,
                                           "shape": shape, "dtype": dtype},
                   name=kwargs.get("name"))


def normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", **kwargs):
    kwargs.pop("ctx", None)
    return _create("_random_normal", [], {"loc": loc, "scale": scale,
                                          "shape": shape, "dtype": dtype},
                   name=kwargs.get("name"))
