"""Symbol — declarative graph API staged onto XLA.

Reference: python/mxnet/symbol/symbol.py (class Symbol, simple_bind:1289,
infer_shape, save/load JSON) and the nnvm graph it fronts.

TPU-native design: a Symbol is a lightweight DAG of op nodes.  There is
no separate GraphExecutor memory planner / engine — ``bind`` builds a
*pure jax function* by topologically evaluating the DAG with jax values
and jits it (executor.py); XLA then does scheduling, fusion, memory
planning and rematerialization (SURVEY.md §7 design stance).  JSON
save/load keeps the nnvm-style {nodes, arg_nodes, heads} structure so
checkpoints look familiar.
"""

from __future__ import annotations

import json

import numpy as _np

from ..base import AttrScope, MXNetError, NameManager
from ..ops import registry as _reg
from ..ops.registry import OP_AUX_INPUTS, OP_INPUT_NAMES, OP_LABEL_INPUTS

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "attr_dict")

    def __init__(self, op, name, attrs, inputs, num_outputs=1, attr_dict=None):
        self.op = op  # None for variables
        self.name = name
        self.attrs = attrs  # op attrs (hashable canonical form)
        self.inputs = inputs  # list of (node, out_index)
        self.num_outputs = num_outputs
        self.attr_dict = attr_dict or {}  # user attrs (ctx_group, lr_mult...)

    @property
    def is_variable(self):
        return self.op is None


class Symbol:
    """An output list of graph nodes (reference: symbol.py Symbol)."""

    def __init__(self, outputs):
        self._outputs = outputs  # list of (node, out_index)

    # ---------------------------------------------------------- topology
    def _topo_nodes(self):
        # iterative post-order DFS: deep chains (unrolled RNNs,
        # get_symbol exports) must not hit the Python recursion limit
        seen = set()
        order = []
        stack = [(node, False) for node, _ in reversed(self._outputs)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for inp, _ in reversed(node.inputs):
                stack.append((inp, False))
        return order

    def list_arguments(self):
        """Input variable names in topo order (reference: ListArguments)."""
        aux = set(self._aux_nodes())
        return [n.name for n in self._topo_nodes()
                if n.is_variable and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_nodes()
        return [n.name for n in self._topo_nodes() if id(n) in aux]

    def _aux_nodes(self):
        """ids of variable nodes feeding aux input slots."""
        aux_ids = set()
        for node in self._topo_nodes():
            if node.op is None:
                continue
            aux_names = OP_AUX_INPUTS.get(node.op, ())
            if not aux_names:
                continue
            input_names = OP_INPUT_NAMES.get(node.op, ())
            for (inp, _), iname in zip(node.inputs, input_names):
                if iname in aux_names and inp.is_variable:
                    aux_ids.add(id(inp))
        return aux_ids

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.num_outputs > 1:
                names.append("%s_output%d" % (node.name, idx))
            else:
                names.append(node.name + "_output" if not node.is_variable
                             else node.name)
        return names

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_variable]

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group [%s]" % ", ".join(
            n.name for n, _ in self._outputs))

    def __iter__(self):
        return (Symbol([out]) for out in self._outputs)

    def __bool__(self):
        # reference: symbol.py __bool__ → NotImplementedForSymbol — a
        # symbol has no truth value; data-dependent branches belong in
        # control-flow ops
        raise MXNetError("Symbol has no truth value: use mx.sym.contrib "
                         "control-flow ops for data-dependent branching")

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            outs = self.list_outputs()
            index = outs.index(index)
        return Symbol([self._outputs[index]])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # ---------------------------------------------------------- attrs
    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0].attr_dict.get(key)
        return None

    def list_attr(self):
        if len(self._outputs) == 1:
            return dict(self._outputs[0][0].attr_dict)
        return {}

    def attr_dict(self):
        ret = {}
        for node in self._topo_nodes():
            d = dict(node.attr_dict)
            if node.op is not None:
                d.update({k: str(v) for k, v in node.attrs.items()})
            if d:
                ret[node.name] = d
        return ret

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attr_dict.update({k: str(v) for k, v in kwargs.items()})

    # ---------------------------------------------------------- arithmetic
    def _binop(self, other, opname, scalarname, reverse=False):
        if isinstance(other, Symbol):
            args = (other, self) if reverse else (self, other)
            return _create(opname, list(args), {})
        if isinstance(other, (int, float)):
            sname = scalarname
            if reverse and "_r" + scalarname[1:] in _REV_SCALARS:
                sname = "_r" + scalarname[1:]
            return _create(sname, [self], {"scalar": float(other)})
        raise TypeError("unsupported operand: %r" % (other,))

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "elemwise_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    def __eq__(self, o):
        return self._binop(o, "elemwise_equal", "_equal_scalar")

    def __ne__(self, o):
        return self._binop(o, "elemwise_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "elemwise_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "elemwise_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "elemwise_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "elemwise_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    # sugar mirroring NDArray
    def reshape(self, shape, **kw):
        return _create("Reshape", [self], {"shape": shape, **kw})

    def sum(self, axis=None, keepdims=False):
        return _create("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _create("mean", [self], {"axis": axis, "keepdims": keepdims})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _create("transpose", [self], {"axes": axes})

    def softmax(self, axis=-1):
        return _create("softmax", [self], {"axis": axis})

    def slice_axis(self, axis, begin, end):
        return _create("slice_axis", [self], {"axis": axis, "begin": begin,
                                              "end": end})

    def astype(self, dtype):
        return _create("Cast", [self], {"dtype": str(_np.dtype(dtype))})

    def get_internals(self):
        """All intermediate outputs as a grouped symbol
        (reference: Symbol.get_internals)."""
        outs = []
        for node in self._topo_nodes():
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        if len(self._outputs) != 1:
            return None
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ---------------------------------------------------------- inference
    def infer_shape(self, *args, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes) via jax.eval_shape
        (reference: infer_shape → fixpoint pass infer_graph_attr_pass.cc;
        here shape propagation is exact tracing, no fixpoint needed)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError as e:
            if "inconsistent shape" in str(e):
                raise  # deterministic user error — retrying cannot help
            return self.infer_shape_partial(*args, **kwargs)
        except Exception:
            # partial infer falls back to the same impl with skips
            return self.infer_shape_partial(*args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = shape
        known.update({k: v for k, v in kwargs.items() if v is not None})

        # infer missing parameter shapes structurally: evaluate with
        # shape-polymorphic placeholders is impossible; instead require
        # data-like inputs and derive parameter shapes via op semantics.
        shapes = _infer_param_shapes(self, known)
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        if not partial and any(s is None for s in arg_shapes + aux_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError("infer_shape: cannot infer %s" % missing)

        out_shapes = None
        if all(s is not None for s in arg_shapes + aux_shapes):
            from ..executor import make_eval_fn

            fn, _meta = make_eval_fn(self, is_train=False)
            arg_avals = [jax.ShapeDtypeStruct(tuple(s), _np.float32)
                         for s in arg_shapes]
            aux_avals = [jax.ShapeDtypeStruct(tuple(s), _np.float32)
                         for s in aux_shapes]
            outs = jax.eval_shape(fn, arg_avals, aux_avals, 0)
            out_shapes = [tuple(o.shape) for o in outs[0]]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dtype = _np.float32
        if args:
            dtype = _np.dtype(args[0]) if args[0] is not None else _np.float32
        # offline-quantized params ("<name>_quantize" by the contrib
        # quantization pass naming) are int8 — the analog of the
        # reference's per-op FInferType forcing kInt8 inputs
        arg_types = [_np.dtype(_np.int8) if n.endswith("_quantize")
                     else _np.dtype(dtype) for n in arg_names]
        return (arg_types,
                [_np.dtype(dtype)] * len(self._outputs),
                [_np.dtype(dtype)] * len(self.list_auxiliary_states()))

    # ---------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate arg/grad arrays from inferred shapes and bind
        (reference: symbol.py:1289 → MXExecutorSimpleBind)."""
        from ..context import current_context
        from ..executor import Executor
        from ..ndarray import zeros

        import os

        backend = os.environ.get("MXNET_SUBGRAPH_BACKEND")
        if backend:
            # reference: bind-time partitioning when
            # MXNET_SUBGRAPH_BACKEND selects a registered property.
            # Partition ONCE and fall through — recursing would re-run
            # the pass per bind and could loop if a property's
            # replacement matches its own selector.
            from .subgraph import list_subgraph_properties, partition_graph

            if backend in list_subgraph_properties():
                part = partition_graph(self, backend)
                if part is not self:
                    self = part
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
        if missing or any(s is None for s in aux_shapes):
            raise MXNetError(
                "simple_bind: cannot infer shapes for %s — provide input "
                "shapes (e.g. data=(batch, ...))" % (missing,))
        type_dict = type_dict or {}
        arg_types, _, _ = self.infer_type()
        args = [zeros(s, ctx=ctx, dtype=type_dict.get(n, t))
                for n, s, t in zip(arg_names, arg_shapes, arg_types)]
        aux = [zeros(s, ctx=ctx) for s in aux_shapes]
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, list):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = dict(grad_req)
        grads = {n: zeros(s, ctx=ctx) for n, s in zip(arg_names, arg_shapes)
                 if reqs.get(n, "write") != "null"}
        return Executor(self, ctx, args, grads, reqs, aux,
                        shared_buffer=shared_buffer)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        """reference: symbol.py bind → GraphExecutor::Bind."""
        from ..executor import Executor

        arg_names = self.list_arguments()
        if isinstance(args, dict):
            args = [args[n] for n in arg_names]
        if isinstance(args_grad, dict):
            grads = args_grad
        elif isinstance(args_grad, (list, tuple)):
            grads = dict(zip(arg_names, args_grad))
        elif args_grad is None:
            grads = {}
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, list):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = dict(grad_req)
        if aux_states is None:
            aux_states = []
        aux_names = self.list_auxiliary_states()
        if isinstance(aux_states, dict):
            aux_states = [aux_states[n] for n in aux_names]
        return Executor(self, ctx, list(args), grads, reqs, list(aux_states))

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def grad(self, wrt):  # pragma: no cover - parity stub
        raise MXNetError("Symbol.grad is deprecated in the reference; "
                         "use bind(grad_req=...) + backward")

    # ---------------------------------------------------------- serialization
    def tojson(self):
        """nnvm-style JSON (reference: MXSymbolSaveToJSON)."""
        nodes = self._topo_nodes()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            if n.is_variable:
                arg_nodes.append(i)
            jnodes.append({
                "op": n.op or "null",
                "name": n.name,
                "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                          for k, v in (n.attrs or {}).items()},
                "inputs": [[node_ids[id(inp)], idx, 0] for inp, idx in n.inputs],
            })
        heads = [[node_ids[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10500],
                                     "mxnet_tpu": ["int", 1]}}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # execution sugar
    def __call__(self, *args, **kwargs):
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        """Replace variable inputs with other symbols (reference: composition).

        Rebuilds the node graph so shared upstream symbols are untouched.
        """
        name_map = {}
        if args:
            vars_ = [n for n in self._topo_nodes() if n.is_variable]
            for v, a in zip(vars_, args):
                name_map[v.name] = a
        name_map.update(kwargs)
        replaced = {}  # id(old var node) -> (replacement node, out idx)
        copies = {}    # id(old op node) -> new node

        def map_entry(inp, idx):
            if id(inp) in replaced:
                return replaced[id(inp)]
            if id(inp) in copies:
                return (copies[id(inp)], idx)
            return (inp, idx)

        for node in self._topo_nodes():
            if node.is_variable:
                if node.name in name_map:
                    replaced[id(node)] = name_map[node.name]._outputs[0]
                continue
            new_inputs = [map_entry(inp, idx) for inp, idx in node.inputs]
            copies[id(node)] = _Node(node.op, node.name, node.attrs, new_inputs,
                                     node.num_outputs, dict(node.attr_dict))
        self._outputs = [map_entry(n, idx) for n, idx in self._outputs]


_REV_SCALARS = {"_rminus_scalar", "_rdiv_scalar", "_rmod_scalar", "_rpower_scalar"}


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
             init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference: symbol.var)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attr = AttrScope.current().get(attr or {})
    if shape is not None:
        attr["__shape__"] = str(shape)
    if lr_mult is not None:
        attr["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attr["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attr["__dtype__"] = str(_np.dtype(dtype))
    if init is not None:
        attr["__init__"] = init if isinstance(init, str) else init.dumps()
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            attr[k] = str(v)
    node = _Node(None, name, {}, [], 1, attr)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one with multiple outputs (reference: sym.Group)."""
    outputs = []
    for s in symbols:
        outputs.extend(s._outputs)
    return Symbol(outputs)


def _create(op_name, input_syms, attrs, name=None):
    """Create an op node symbol; auto-create missing input variables."""
    op = _reg.get(op_name)
    attrs = {k: v for k, v in attrs.items() if v is not None}
    attrs = op.canonicalize_attrs(attrs)
    hint = op.name.lower().lstrip("_")
    name = NameManager.current().get(name, hint)
    attr_dict = AttrScope.current().get({})

    slot_names = OP_INPUT_NAMES.get(op.name, ())
    inputs = []
    for pos, s in enumerate(input_syms):
        if isinstance(s, Symbol):
            if len(s._outputs) != 1:
                raise MXNetError("cannot use grouped symbol as single input")
            inputs.append(s._outputs[0])
        elif s is None:
            # named optional slot passed as None: omit if the attrs say the
            # op runs without it, otherwise auto-create its variable
            sname = slot_names[pos] if pos < len(slot_names) else None
            if sname is None:
                raise TypeError("%s: input %d is None" % (op.name, pos))
            if sname == "bias" and attrs.get("no_bias", False) and \
                    op.name in ("Convolution", "FullyConnected",
                                "Deconvolution"):
                # these op fns take bias as an optional trailing arg; for
                # every other op the positional slot must stay occupied
                continue
            v = Variable("%s_%s" % (name, sname))
            inputs.append(v._outputs[0])
        else:
            raise TypeError("symbol inputs must be Symbols")

    # auto-create missing parameter variables (reference autogen behaviour)
    if op.name == "Custom" and attrs.get("op_type"):
        # a custom op declares its own argument list; unprovided tails
        # become "<name>_<arg>" variables (reference: custom.cc wiring
        # label-style args, e.g. mx.sym.Custom(data=..., name='softmax')
        # growing 'softmax_label').  Errors (unknown op_type, prop
        # __init__ rejecting kwargs) surface HERE, at creation time.
        from ..ops.custom import _prop_for

        extra = {k: v for k, v in attrs.items() if k != "op_type"}
        prop = _prop_for(attrs["op_type"], extra)
        for iname in tuple(prop.list_arguments())[len(inputs):]:
            v = Variable("%s_%s" % (name, iname))
            inputs.append(v._outputs[0])
    needed = OP_INPUT_NAMES.get(op.name, ())
    if needed and len(inputs) < len(needed):
        # per-op no_bias default: Deconvolution defaults to NO bias in
        # the reference (deconvolution-inl.h set_default(true)), unlike
        # Convolution/FullyConnected — auto-creating a live bias there
        # would grow a trainable param reference checkpoints lack.  The
        # op fn's signature default IS the reference default
        import inspect

        default_no_bias = False
        try:
            sig_p = inspect.signature(op.fn).parameters.get("no_bias")
            if sig_p is not None and sig_p.default is not inspect.Parameter.empty:
                default_no_bias = bool(sig_p.default)
        except (TypeError, ValueError):
            pass
        no_bias = attrs.get("no_bias", default_no_bias)
        use_seq = attrs.get("use_sequence_length", False)
        for iname in needed[len(inputs):]:
            if iname == "bias" and no_bias:
                continue
            if iname == "sequence_length" and not use_seq:
                continue
            if iname in ("data_lengths", "label_lengths"):
                continue
            if iname == "gamma" and op.name == "LeakyReLU" and \
                    attrs.get("act_type", "leaky") != "prelu":
                # only prelu carries a learned slope parameter
                continue
            v = Variable("%s_%s" % (name, iname))
            inputs.append(v._outputs[0])

    nout = op.nout(attrs)
    node = _Node(op.name, name, attrs, inputs, nout, attr_dict)
    return Symbol([(node, i) for i in range(nout)]) if nout > 1 else \
        Symbol([(node, 0)])


def load_json(json_str):
    """Load from nnvm-style JSON (reference: MXSymbolCreateFromJSON;
    versioned upgrade pass src/nnvm/legacy_json_util.cc is unnecessary —
    we only load our own v1 format plus plain reference graphs)."""
    g = json.loads(json_str)
    jnodes = g["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = jn.get("attrs", jn.get("param", {})) or {}
        parsed = {}
        for k, v in attrs.items():
            parsed[k] = _parse_attr_value(v)
        op = jn["op"] if jn["op"] != "null" else None
        inputs = [(nodes[i], idx) for i, idx, *_ in jn.get("inputs", [])]
        nout = 1
        if op is not None:
            try:
                nout = _reg.get(op).nout(_reg.get(op).canonicalize_attrs(parsed))
            except MXNetError:
                pass
        node = _Node(op, jn["name"], parsed if op else {}, inputs, nout)
        nodes.append(node)
    heads = [(nodes[i], idx) for i, idx, *_ in g["heads"]]
    return Symbol(heads)


def _parse_attr_value(v):
    if not isinstance(v, str):
        return v
    try:
        return json.loads(v)
    except (ValueError, TypeError):
        pass
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    if v.startswith("(") and v.endswith(")"):
        try:
            inner = v[1:-1].strip().rstrip(",")
            if not inner:
                return ()
            return tuple(int(x) if "." not in x else float(x)
                         for x in inner.split(","))
        except ValueError:
            pass
    return v


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ops whose inputs[0] and output share a shape exactly (for the partial
# unification pass; broadcast variants are excluded — not invertible)
_UNIFY_UNARY = {"relu", "sigmoid", "tanh", "softsign", "Activation",
                "softmax", "log_softmax", "BatchNorm", "LeakyReLU",
                "Dropout", "identity", "negative", "LayerNorm"}
_UNIFY_ELEMWISE = {"elemwise_add", "elemwise_sub", "elemwise_mul",
                   "elemwise_div"}


def _propagate_partial(symbol, known):
    """Bidirectional fixpoint over PARTIAL shapes (0 = unknown dim in a
    Variable's shape attr — reference: test_infer_shape.py
    test_incomplete_infer_*, src/executor/infer_graph_attr_pass.cc's
    forward/backward iterations).  Returns {var_name: complete tuple}
    for every variable the unification resolves; structural rules cover
    elemwise, shape-preserving unaries, FullyConnected, Convolution
    (stride-1 backward), SliceChannel, and Concat."""
    nodes = symbol._topo_nodes()
    var_shapes = {}
    out_shapes = {}

    def vec_of(shape):
        return [None if int(d) == 0 else int(d) for d in shape]

    for node in nodes:
        if node.is_variable:
            if node.name in known:
                var_shapes[node.name] = vec_of(known[node.name])
            elif "__shape__" in node.attr_dict:
                var_shapes[node.name] = vec_of(
                    _parse_attr_value(node.attr_dict["__shape__"]))

    state = {"changed": False}

    def get(inp, idx):
        if inp.is_variable:
            return var_shapes.get(inp.name)
        return out_shapes.get((id(inp), idx))

    def unify(a, b, what):
        if a is None:
            return list(b) if b is not None else None
        if b is None:
            return list(a)
        if len(a) != len(b):
            raise MXNetError("infer_shape: rank mismatch at %s: %r vs %r"
                             % (what, a, b))
        out = []
        for x, y in zip(a, b):
            if x is not None and y is not None and x != y:
                raise MXNetError("infer_shape: dim mismatch at %s: %r vs %r"
                                 % (what, a, b))
            out.append(x if x is not None else y)
        return out

    def _merge(store, key, vec, what):
        merged = unify(store.get(key), vec, what)
        if merged != store.get(key):
            store[key] = merged
            state["changed"] = True

    def put(inp, idx, vec, what):
        if vec is None:
            return
        if inp.is_variable:
            _merge(var_shapes, inp.name, vec, what)
        else:
            _merge(out_shapes, (id(inp), idx), vec, what)

    def put_out(node, idx, vec):
        if vec is not None:
            _merge(out_shapes, (id(node), idx), vec, node.name)

    def ival(attrs, key, default=None):
        v = attrs.get(key, default)
        if isinstance(v, str):
            v = _parse_attr_value(v)
        return v

    def step(node):
        a = node.attrs
        ins = node.inputs
        op = node.op
        me = lambda: out_shapes.get((id(node), 0))
        if op in _UNIFY_ELEMWISE:
            # reference elemwise_* requires identical shapes, so dims
            # unify across operands and result.  This runtime tolerates
            # broadcasting; when a known dim is 1 against a larger dim
            # the node is broadcast-style — skip it (no raise, no
            # back-fill) rather than force the same-shape contract.
            vecs = [me()] + [get(inp, idx) for inp, idx in ins]
            known = [v for v in vecs if v is not None]
            if any(len(a) == len(b) and any(
                    x is not None and y is not None and x != y and
                    1 in (x, y) for x, y in zip(a, b))
                   for i, a in enumerate(known) for b in known[i + 1:]):
                return
            merged = me()
            for inp, idx in ins:
                merged = unify(merged, get(inp, idx), node.name)
            for inp, idx in ins:
                put(inp, idx, merged, node.name)
            put_out(node, 0, merged)
        elif op in _UNIFY_UNARY and ins:
            inp, idx = ins[0]
            merged = unify(me(), get(inp, idx), node.name)
            put(inp, idx, merged, node.name)
            put_out(node, 0, merged)
        elif op == "Flatten" and ins:
            # out = (batch, prod(rest)); the batch dim unifies both ways
            data = get(*ins[0])
            out = me()
            batch = data[0] if data is not None else None
            if batch is None and out is not None:
                batch = out[0]
            tail = None
            if data is not None and all(d is not None for d in data[1:]):
                tail = 1
                for d in data[1:]:
                    tail *= d
            put_out(node, 0, [batch, tail])
            if data is not None:
                put(ins[0][0], ins[0][1], [batch] + data[1:], node.name)
        elif op == "FullyConnected":
            nh = ival(a, "num_hidden")
            if nh is None:
                return
            nh = int(nh)
            flatten = bool(ival(a, "flatten", True))
            data = get(*ins[0])
            out = me()
            batch = None
            if data is not None:
                batch = data[0]
            if out is not None:
                batch = out[0] if batch is None else batch
            if flatten:
                put_out(node, 0, [batch, nh])
            elif data is not None:
                # flatten=False: only the last axis projects
                put_out(node, 0, [batch] + data[1:-1] + [nh])
            elif out is not None:
                put_out(node, 0, [batch] + out[1:-1] + [nh])
            if data is not None:
                lead = [batch] + data[1:]
                # non-batch data dims also flow back from out when
                # flatten=False (they pass through unchanged)
                if not flatten and out is not None and \
                        len(out) == len(data):
                    lead = [batch] + [
                        d if d is not None else o
                        for d, o in zip(data[1:-1], out[1:-1])] + [data[-1]]
                put(ins[0][0], ins[0][1], lead, node.name)
                rest = data[1:] if flatten else data[-1:]
                if all(d is not None for d in rest) and len(ins) > 1:
                    in_dim = 1
                    for d in rest:
                        in_dim *= d
                    put(ins[1][0], ins[1][1], [nh, in_dim], node.name)
        elif op == "Convolution" and ival(a, "layout", "NCHW") == "NCHW":
            k = tuple(ival(a, "kernel", ()))
            nf = ival(a, "num_filter")
            if len(k) != 2 or nf is None:
                return
            nf = int(nf)
            s = tuple(ival(a, "stride", (1, 1)) or (1, 1))
            p = tuple(ival(a, "pad", (0, 0)) or (0, 0))
            dl = tuple(ival(a, "dilate", (1, 1)) or (1, 1))
            data = get(*ins[0])
            out = me()
            if (data is not None and len(data) != 4) or \
                    (out is not None and len(out) != 4):
                raise MXNetError("infer_shape: Convolution at %s expects "
                                 "rank-4 NCHW shapes" % node.name)
            batch = (data[0] if data is not None else None)
            if batch is None and out is not None:
                batch = out[0]
            fwd = [batch, nf, None, None]
            bwd_sp = [None, None]
            for i in range(2):
                din = data[2 + i] if data is not None else None
                dout = out[2 + i] if out is not None else None
                eff = dl[i] * (k[i] - 1)
                if din is not None:
                    fwd[2 + i] = (din + 2 * p[i] - eff - 1) // s[i] + 1
                if dout is not None and s[i] == 1:
                    # s=1: out = in + 2p - eff, exactly invertible
                    bwd_sp[i] = dout - 2 * p[i] + eff
            put_out(node, 0, fwd)
            if data is not None:
                put(ins[0][0], ins[0][1],
                    [batch, data[1], bwd_sp[0] if data[2] is None else data[2],
                     bwd_sp[1] if data[3] is None else data[3]], node.name)
        elif op == "SliceChannel":
            n = ival(a, "num_outputs")
            if n is None:
                return
            n = int(n)
            ax = int(ival(a, "axis", 1))
            squeeze = bool(ival(a, "squeeze_axis", False))
            data = get(*ins[0])
            for i in range(node.num_outputs):
                out_i = out_shapes.get((id(node), i))
                if data is not None:
                    ax_ = ax % len(data)
                    if squeeze:
                        vec = data[:ax_] + data[ax_ + 1:]
                    else:
                        vec = list(data)
                        vec[ax_] = (None if data[ax_] is None
                                    else data[ax_] // n)
                    put_out(node, i, vec)
                if out_i is not None:
                    if squeeze:
                        ax_in = ax % (len(out_i) + 1)
                        back = out_i[:ax_in] + [n] + out_i[ax_in:]
                    else:
                        back = list(out_i)
                        back[ax % len(out_i)] = (
                            None if out_i[ax % len(out_i)] is None
                            else out_i[ax % len(out_i)] * n)
                    put(ins[0][0], ins[0][1], back, node.name)
        elif op == "Concat":
            dim = int(ival(a, "dim", 1))
            vecs = [get(inp, idx) for inp, idx in ins]
            out = me()
            rank = next((len(v) for v in vecs if v is not None),
                        len(out) if out is not None else None)
            if rank is None:
                return
            d = dim % rank
            # unify non-concat axes across everything
            proto = [None] * rank
            for v in vecs + [out]:
                if v is None:
                    continue
                if len(v) != rank:
                    raise MXNetError("infer_shape: concat rank mismatch "
                                     "at %s" % node.name)
                for i in range(rank):
                    if i != d and v[i] is not None:
                        if proto[i] is not None and proto[i] != v[i]:
                            raise MXNetError(
                                "infer_shape: concat dim mismatch at %s"
                                % node.name)
                        proto[i] = v[i]
            for (inp, idx), v in zip(ins, vecs):
                vec = list(proto)
                vec[d] = v[d] if v is not None else None
                put(inp, idx, vec, node.name)
            dims = [v[d] if v is not None else None for v in vecs]
            out_d = (sum(dims) if all(x is not None for x in dims)
                     else None)
            if out_d is None and out is not None and out[d] is not None \
                    and sum(x is None for x in dims) == 1:
                missing = out[d] - sum(x for x in dims if x is not None)
                i = dims.index(None)
                vec = list(proto)
                vec[d] = missing
                put(ins[i][0], ins[i][1], vec, node.name)
                out_d = out[d]
            outv = list(proto)
            outv[d] = out_d
            put_out(node, 0, outv)

    op_nodes = [n for n in nodes if not n.is_variable]
    for _ in range(100):
        state["changed"] = False
        # forward then reverse half-sweeps: backward information crosses
        # the whole graph per iteration, so deep chains (100+-step
        # unrolled RNNs) converge in a handful of sweeps instead of one
        # node per sweep
        for node in op_nodes:
            step(node)
        for node in reversed(op_nodes):
            step(node)
        if not state["changed"]:
            break

    return {name: tuple(v) for name, v in var_shapes.items()
            if v is not None and all(d is not None for d in v)}


def _infer_param_shapes(symbol, known):
    """Forward shape propagation through the DAG, solving parameter
    shapes from op semantics (the TPU analog of the reference's shape
    inference attributes, src/executor/infer_graph_attr_pass.cc:325)."""
    shapes = dict(known)
    node_out_shapes = {}

    def _has_partial():
        for v in shapes.values():
            if v is not None and any(int(d) == 0 for d in v):
                return True
        for node in symbol._topo_nodes():
            if node.is_variable and node.name not in shapes \
                    and "__shape__" in node.attr_dict:
                s = _parse_attr_value(node.attr_dict["__shape__"])
                if any(int(d) == 0 for d in s):
                    return True
        return False

    if _has_partial():
        # bidirectional unification resolves 0-marked dims first; only
        # fully-resolved variables feed the (complete-shape) main pass
        solved = _propagate_partial(symbol, known)
        shapes = {k: v for k, v in shapes.items()
                  if v is None or not any(int(d) == 0 for d in v)}
        shapes.update(solved)

    def entry_shape(inp, idx):
        # Cast is exactly shape-preserving: when a cast's own output
        # shape is still unknown (its source was a then-unsolved
        # parameter, e.g. behind an AMP-inserted cast), read through
        # the chain instead of giving up — this keeps infer_shape
        # single-pass even with casts between params and consumers
        while True:
            if inp.is_variable:
                return tuple(shapes[inp.name]) if inp.name in shapes \
                    else None
            outs = node_out_shapes.get(id(inp))
            if outs is not None:
                return outs[idx]
            if inp.op == "Cast" and inp.inputs:
                inp, idx = inp.inputs[0]
                continue
            return None

    def get_in_shapes(node):
        return [entry_shape(inp, idx) for inp, idx in node.inputs]

    import jax

    for node in symbol._topo_nodes():
        if node.is_variable:
            if node.name not in shapes and "__shape__" in node.attr_dict:
                s = tuple(_parse_attr_value(node.attr_dict["__shape__"]))
                if not any(int(d) == 0 for d in s):  # partials solved above
                    shapes[node.name] = s
            continue
        in_shapes = get_in_shapes(node)
        # solve unknown parameter-variable shapes from op semantics
        _solve_params(node, in_shapes, shapes)
        in_shapes = get_in_shapes(node)
        if any(s is None for s in in_shapes):
            node_out_shapes[id(node)] = None
            continue
        op = _reg.get(node.op)
        fn = op.bind_attrs(node.attrs)
        try:
            avals = [jax.ShapeDtypeStruct(s, _np.float32) for s in in_shapes]
            if node.op in _RANDOMISH:
                out = jax.eval_shape(lambda *xs: fn(jax.random.PRNGKey(0), *xs),
                                     *avals)
            else:
                out = jax.eval_shape(fn, *avals)
        except Exception:
            node_out_shapes[id(node)] = None
            continue
        if isinstance(out, (tuple, list)):
            node_out_shapes[id(node)] = [tuple(o.shape) for o in out]
        else:
            node_out_shapes[id(node)] = [tuple(out.shape)]
    return shapes


_RANDOMISH = {"Dropout"}

# parsed sub-symbols for _subgraph_exec param-shape solving, keyed by
# the serialized JSON (same key the executor-side cache uses)
_SUBGRAPH_SOLVE = {}


def _solve_params(node, in_shapes, shapes):
    """Derive parameter shapes for common layers (FC/conv/BN/embedding)."""
    if node.op == "Custom" and in_shapes and in_shapes[0] is not None:
        # the prop's infer_shape derives the remaining argument shapes
        # from the known ones (reference: CustomOpProp.infer_shape).
        # User infer_shape code may assume fully-known inputs, so only
        # partially-known calls guard; errors on fully-known shapes are
        # the user's bug and propagate.
        from ..ops.custom import _prop_for

        a = node.attrs
        prop = _prop_for(a["op_type"],
                         {k: v for k, v in a.items() if k != "op_type"})
        arg_list = [list(s) if s is not None else None for s in in_shapes]
        if any(s is None for s in arg_list):
            try:
                solved, _, _ = prop.infer_shape(arg_list)
            except Exception:
                return
        else:
            solved, _, _ = prop.infer_shape(arg_list)
        for i, s2 in enumerate(solved[:len(node.inputs)]):
            if s2 is not None:
                inp, _ = node.inputs[i]
                if inp.is_variable and inp.name not in shapes:
                    shapes[inp.name] = tuple(int(x) for x in s2)
        return
    if node.op == "_subgraph_exec":
        # the ops whose semantics solve these shapes live inside the
        # serialized sub-symbol: recurse, then pull solved variable
        # shapes back onto the outer inputs, which bind positionally in
        # list_inputs() order (ops/custom.py subgraph_exec contract)
        sj = node.attrs.get("subgraph_json")
        if sj is None or not any(s is not None for s in in_shapes) \
                or not any(s is None for s in in_shapes):
            return
        cached = _SUBGRAPH_SOLVE.get(sj)
        if cached is None:
            sub = load_json(sj)
            cached = (sub, sub.list_inputs())
            _SUBGRAPH_SOLVE[sj] = cached
        sub, in_names = cached
        if len(in_names) != len(node.inputs):
            return
        inner_known = {n: s for n, s in zip(in_names, in_shapes)
                       if s is not None}
        solved = _infer_param_shapes(sub, inner_known)
        for i, nm in enumerate(in_names):
            s2 = solved.get(nm)
            if s2 is None or in_shapes[i] is not None:
                continue
            inp, _ = node.inputs[i]
            while inp.op == "Cast" and inp.inputs:
                inp = inp.inputs[0][0]
            if inp.is_variable and inp.name not in shapes:
                shapes[inp.name] = tuple(int(x) for x in s2)
        return
    names = OP_INPUT_NAMES.get(node.op, ())
    if not names or in_shapes[0] is None:
        return
    data_shape = in_shapes[0]
    a = node.attrs

    def setv(i, shape, strict=True):
        inp, _ = node.inputs[i]
        # the structural constraint lands on the source variable even
        # through dtype-only Cast chains (AMP inserts one between each
        # parameter and its consumer; casts never change shape)
        while inp.op == "Cast" and inp.inputs:
            inp = inp.inputs[0][0]
        if not inp.is_variable:
            return
        want = tuple(int(x) for x in shape)
        have = shapes.get(inp.name)
        if have is None:
            shapes[inp.name] = want
        elif strict and tuple(have) != want:
            # a provided shape contradicting a STRUCTURAL op constraint
            # (weight/bias dims) is an error, not a silent override
            # (reference: InferShape consistency, test_mlp2_infer_error).
            # Heuristic hints (label mirroring) pass strict=False — the
            # ops accept broadcastable label shapes at runtime.
            raise MXNetError(
                "infer_shape: inconsistent shape for %r: provided %r, "
                "op semantics of %r require %r"
                % (inp.name, tuple(have), node.name, want))

    if node.op == "FullyConnected":
        nh = int(a.get("num_hidden", 1))
        flat = a.get("flatten", True)
        in_dim = int(_np.prod(data_shape[1:])) if flat else data_shape[-1]
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "weight":
                setv(i, (nh, in_dim))
            elif nm == "bias":
                setv(i, (nh,))
    elif node.op in ("Convolution", "Deconvolution"):
        k = tuple(a.get("kernel", ()))
        nf = int(a.get("num_filter", 1))
        ng = int(a.get("num_group", 1))
        layout = a.get("layout") or "NCHW"
        channel_last = layout.endswith("C")
        cin = data_shape[-1] if channel_last else data_shape[1]
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "weight":
                if node.op == "Convolution":
                    # OIHW for channel-first, OHWI for channel-last
                    want = ((nf,) + k + (cin // ng,) if channel_last
                            else (nf, cin // ng) + k)
                    setv(i, want)
                else:
                    want = ((cin,) + k + (nf // ng,) if channel_last
                            else (cin, nf // ng) + k)
                    setv(i, want)
            elif nm == "bias":
                setv(i, (nf,))
    elif node.op in ("_contrib_quantized_fully_connected",
                     "_contrib_quantized_conv"):
        # int8 layers: weight/bias like their float twins + (1,) range
        # scalars (reference: quantized_conv.cc / quantized_fully_connected.cc
        # shape functions)
        if node.op == "_contrib_quantized_fully_connected":
            nh = int(a.get("num_hidden", 1))
            flat = a.get("flatten", True)
            in_dim = int(_np.prod(data_shape[1:])) if flat else data_shape[-1]
            wshape, bshape = (nh, in_dim), (nh,)
        else:
            k = tuple(a.get("kernel", ()))
            nf = int(a.get("num_filter", 1))
            ng = int(a.get("num_group", 1))
            wshape, bshape = (nf, data_shape[1] // ng) + k, (nf,)
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "weight":
                setv(i, wshape)
            elif nm == "bias":
                setv(i, bshape)
            elif nm.startswith(("min_", "max_")):
                setv(i, (1,))
    elif node.op in ("BatchNorm",):
        ax = int(a.get("axis", 1)) % len(data_shape)
        c = data_shape[ax]
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm != "data":
                setv(i, (c,))
    elif node.op in ("LayerNorm",):
        ax = int(a.get("axis", -1)) % len(data_shape)
        c = data_shape[ax]
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm != "data":
                setv(i, (c,))
    elif node.op == "InstanceNorm":
        c = data_shape[1]
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm != "data":
                setv(i, (c,))
    elif node.op == "Embedding":
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "weight":
                setv(i, (int(a.get("input_dim", 1)), int(a.get("output_dim", 1))))
    elif node.op == "RNN":
        # data (T, B, in) fixes the packed vector and state shapes
        # (reference: rnn-inl.h RNNShape)
        from ..ops.rnn import rnn_param_size

        h = int(a.get("state_size", 0))
        layers = int(a.get("num_layers", 1))
        dirs = 2 if a.get("bidirectional") else 1
        t, b, din = data_shape
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "parameters":
                setv(i, (rnn_param_size(layers, din, h,
                                        bool(a.get("bidirectional")),
                                        a.get("mode", "lstm")),))
            elif nm in ("state", "state_cell"):
                setv(i, (layers * dirs, b, h))
    elif node.op == "LeakyReLU" and a.get("act_type") == "prelu":
        if len(node.inputs) > 1:
            setv(1, (data_shape[1],))
    elif node.op in OP_LABEL_INPUTS:
        # label shape mirrors data minus class axis for classifier heads
        for i, nm in enumerate(names[:len(node.inputs)]):
            if nm == "label":
                if node.op in ("SoftmaxOutput", "SVMOutput"):
                    if a.get("multi_output"):
                        setv(i, (data_shape[0],) + data_shape[2:],
                             strict=False)
                    else:
                        setv(i, data_shape[:-1], strict=False)
                else:
                    setv(i, data_shape, strict=False)
