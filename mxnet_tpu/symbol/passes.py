"""Pass manager over the Symbol DAG — verified rewrites by construction.

Relay-style (arxiv 1810.00952) composable IR -> IR transforms: a
:class:`Pass` wraps one graph rewrite, and the manager re-runs the
graph verifier (:mod:`.verify`) on the rewrite's output before anyone
downstream can bind it.  A pass that produces an invalid graph fails
loudly with the pass *and* the finding named — it never hands a broken
DAG to the executor, where the same fault would surface as an opaque
trace error deep inside jit.

Per-pass bookkeeping lands in :mod:`..runtime_stats` (the
``graph_passes`` snapshot section): run counts, verify wall time, node
deltas, and — when the context opts in with ``measure_cost=True`` —
XLA-reported flops/bytes before and after the rewrite, so
``runtime_stats.report()`` and ``--compare`` show what a rewrite
actually bought.  Cost measurement compiles the whole graph twice and
is therefore opt-in.

Identity contract: a pass that has nothing to do must return the input
Symbol *itself* (not a reconstruction).  The manager skips
re-verification for identity returns — callers like
``simple_bind``'s ``part is not self`` check rely on object identity,
and verifying an unchanged input would turn pre-existing oddities in
user graphs into new errors.
"""

from __future__ import annotations

import time as _time

from ..base import MXNetError
from .verify import verify_graph

__all__ = ["Pass", "FunctionPass", "PassContext", "PassError",
           "sequential", "pass_stats_snapshot", "reset_pass_stats"]


class PassError(MXNetError):
    """A pass produced an invalid graph (or failed internally)."""


class PassContext:
    """Shared knobs for one pass-pipeline run.

    ``input_shapes`` / ``input_dtypes`` seed the verifier's abstract
    interpretation (without them verification is partial: structural +
    cache-key checks always run in full).  ``verify=False`` disables
    post-pass verification (escape hatch; production callers keep it
    on).  ``measure_cost=True`` additionally compiles the graph before
    and after each pass and records XLA flops/bytes deltas —
    expensive, off by default.  ``options`` is a free-form dict for
    pass-specific parameters.
    """

    def __init__(self, input_shapes=None, input_dtypes=None, options=None,
                 verify=True, measure_cost=False):
        self.input_shapes = dict(input_shapes or {})
        self.input_dtypes = dict(input_dtypes or {})
        self.options = dict(options or {})
        self.verify = verify
        self.measure_cost = measure_cost


# {pass name: {"runs", "changed", "verify_seconds", "nodes_before",
#              "nodes_after", "flops_before", "flops_after",
#              "bytes_before", "bytes_after"}}
_PASS_STATS = {}


def reset_pass_stats():
    _PASS_STATS.clear()


def pass_stats_snapshot():
    """Deep copy of per-pass stats for runtime_stats.snapshot()."""
    return {name: dict(st) for name, st in _PASS_STATS.items()}


def _node_count(sym):
    return sum(1 for _ in sym._topo_nodes())


def _graph_cost(sym, ctx):
    """XLA cost analysis of the whole graph: {"flops", "bytes"} or None.

    Compiles the inference-mode eval fn on avals derived from the
    context's input shapes — the same lowering the executor would jit.
    """
    try:
        import jax

        from ..executor import make_eval_fn
        from ..ops import registry as _reg
        from .verify import variable_dtypes

        arg_shapes, _out, aux_shapes = sym.infer_shape(**ctx.input_shapes)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            return None
        dtypes = variable_dtypes(sym, ctx.input_dtypes)
        args = sym.list_arguments()
        auxs = sym.list_auxiliary_states()
        arg_avals = [jax.ShapeDtypeStruct(tuple(s), dtypes.get(n, "float32"))
                     for n, s in zip(args, arg_shapes)]
        aux_avals = [jax.ShapeDtypeStruct(tuple(s), dtypes.get(n, "float32"))
                     for n, s in zip(auxs, aux_shapes)]
        fn, _meta = make_eval_fn(sym, is_train=False)
        compiled = jax.jit(fn).lower(arg_avals, aux_avals, 0).compile()
        cost = _reg.compiled_cost(compiled)
        if not cost:
            return None
        return {"flops": cost.get("flops"),
                "bytes": cost.get("bytes_accessed")}
    except Exception:
        return None


def _record(name, changed, verify_seconds, nodes_before, nodes_after,
            cost_before, cost_after):
    st = _PASS_STATS.setdefault(name, {
        "runs": 0, "changed": 0, "verify_seconds": 0.0,
        "nodes_before": None, "nodes_after": None,
        "flops_before": None, "flops_after": None,
        "bytes_before": None, "bytes_after": None,
    })
    st["runs"] += 1
    st["changed"] += 1 if changed else 0
    st["verify_seconds"] += verify_seconds
    st["nodes_before"] = nodes_before
    st["nodes_after"] = nodes_after
    if cost_before:
        st["flops_before"] = cost_before.get("flops")
        st["bytes_before"] = cost_before.get("bytes")
    if cost_after:
        st["flops_after"] = cost_after.get("flops")
        st["bytes_after"] = cost_after.get("bytes")
    try:
        from .. import runtime_stats as _rts

        _rts.inc("graph_pass_runs")
        if changed:
            _rts.inc("graph_pass_rewrites")
    except Exception:
        pass


class Pass:
    """Base class: subclasses set ``name`` and implement ``run``."""

    name = "pass"

    def run(self, sym, ctx):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, sym, ctx=None):
        ctx = ctx or PassContext()
        nodes_before = _node_count(sym)
        cost_before = _graph_cost(sym, ctx) if ctx.measure_cost else None
        try:
            new_sym = self.run(sym, ctx)
        except PassError:
            raise
        except MXNetError as e:
            raise PassError("pass %r failed: %s" % (self.name, e)) from e
        changed = new_sym is not sym
        verify_seconds = 0.0
        if changed and ctx.verify:
            t0 = _time.perf_counter()
            result = verify_graph(new_sym,
                                  input_shapes=ctx.input_shapes,
                                  input_dtypes=ctx.input_dtypes)
            verify_seconds = _time.perf_counter() - t0
            if not result.ok:
                first = result.findings[0]
                raise PassError(
                    "pass %r produced an invalid graph — refusing to "
                    "hand it to the executor.  First finding: %s\n"
                    "All findings:\n%s"
                    % (self.name, first.format(), result.format()))
        nodes_after = nodes_before if not changed else _node_count(new_sym)
        cost_after = None
        if ctx.measure_cost:
            cost_after = cost_before if not changed \
                else _graph_cost(new_sym, ctx)
        _record(self.name, changed, verify_seconds, nodes_before,
                nodes_after, cost_before, cost_after)
        return new_sym

    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)


class FunctionPass(Pass):
    """Wrap a ``fn(sym, ctx) -> sym`` as a Pass."""

    def __init__(self, name, fn):
        self.name = name
        self._fn = fn

    def run(self, sym, ctx):
        return self._fn(sym, ctx)


class _Sequential(Pass):
    def __init__(self, passes, name="sequential"):
        self.name = name
        self.passes = list(passes)

    def run(self, sym, ctx):  # pragma: no cover - __call__ overridden
        raise NotImplementedError

    def __call__(self, sym, ctx=None):
        ctx = ctx or PassContext()
        for p in self.passes:
            sym = p(sym, ctx)
        return sym


def sequential(passes, name="sequential"):
    """Compose passes left-to-right; each is individually verified."""
    return _Sequential(passes, name=name)
