"""Automatic mixed precision — the first production pass on the pass
manager.

``amp_convert`` sweeps a Symbol graph to bfloat16 compute while keeping
f32 *islands* where reduced precision is known to hurt:

* normalization ops (BatchNorm/LayerNorm/InstanceNorm/L2Normalization)
  — small-variance statistics cancel catastrophically in bf16;
* softmax / log_softmax and every loss head in ``OP_LABEL_INPUTS``
  (SoftmaxOutput & friends) — exp/sum reductions plus the
  optimizer-visible loss stay f32;
* explicit reductions (sum, mean, prod, norm, moments) — long
  accumulation chains need f32 accumulators;
* anything the caller lists in ``excluded`` (by node name).

Master weights stay f32: variables are *not* retyped — a single cached
``Cast`` node per (producer output, dtype) converts values at the
precision boundary, so the optimizer, initializers and checkpoints see
the same f32 parameters as before.  Graph heads are cast back to f32
(optimizer- and metric-visible outputs keep their dtype contract).
Integer inputs (Embedding indices, sequence lengths) are never cast —
the shared :func:`..symbol.verify.variable_dtypes` seeding knows an
int32 when it sees one.  ``Cast``/``Custom``/``_subgraph_exec`` and
the int8 quantization family are left untouched, with their original
input dtypes restored at the boundary.

The pass-manager wrapper re-verifies the converted graph (shape/dtype
abstract interpretation included) before anyone can bind it, and the
numerics contract — loss parity vs the f32 graph within documented
tolerance — is pinned in tests/test_graph_passes.py.
"""

from __future__ import annotations

import numpy as _np

from ..base import np_dtype as _np_dtype
from ..ops import registry as _reg
from ..ops.registry import OP_LABEL_INPUTS
from .passes import Pass, PassContext
from .symbol import Symbol, _Node
from .verify import variable_dtypes

__all__ = ["AMPPass", "amp_convert", "FP32_ISLAND_OPS"]

# ops whose *computation* stays f32 (inputs cast back up at the edge)
FP32_ISLAND_OPS = frozenset(
    {"BatchNorm", "LayerNorm", "InstanceNorm", "L2Normalization",
     "softmax", "log_softmax", "SoftmaxActivation",
     "sum", "mean", "prod", "nansum", "nanprod", "norm", "moments"}
    | set(OP_LABEL_INPUTS))

# ops AMP must not restructure at all: casts themselves, opaque
# callbacks, spliced subgraphs, and the int8 quantization family
# (their dtype choreography is the whole point of that pass)
_NEVER_TOUCH = frozenset({"Cast", "Custom", "_subgraph_exec"})


def _untouchable(op_name):
    if op_name in _NEVER_TOUCH:
        return True
    low = op_name.lower()
    return "quantize" in low or "dequantize" in low


def _is_float(dtype):
    try:
        return _np.issubdtype(_np.dtype(dtype), _np.floating) \
            or str(dtype) == "bfloat16"
    except TypeError:
        return str(dtype) == "bfloat16"


def _amp_impl(sym, target_dtype="bfloat16", excluded=(), input_dtypes=None):
    """Rebuild ``sym`` with bf16 compute + f32 islands; returns ``sym``
    itself when nothing converts (identity contract for the pass
    manager)."""
    target = _np_dtype(target_dtype)
    f32 = _np.dtype(_np.float32)
    excluded = set(excluded)
    var_dtypes = variable_dtypes(sym, input_dtypes)
    cast_op = _reg.get("Cast")

    mapped = {}     # id(old node) -> new node
    tags = {}       # (id(new node), out idx) -> np dtype (best effort)
    casts = {}      # (id(new node), out idx, dtype str) -> cast node
    changed = [False]

    def cast_to(node, idx, dtype):
        """Cached Cast node converting output ``idx`` of ``node``."""
        key = (id(node), idx, str(dtype))
        hit = casts.get(key)
        if hit is not None:
            return hit
        short = "bf16" if dtype == target and target != f32 else \
            str(dtype).replace("float", "f")
        attrs = cast_op.canonicalize_attrs(
            {"dtype": "bfloat16" if short == "bf16" else str(dtype)})
        cnode = _Node("Cast", "%s_amp_cast%d_%s" % (node.name, idx, short),
                      attrs, [(node, idx)], 1, {})
        casts[key] = cnode
        tags[(id(cnode), 0)] = dtype
        changed[0] = True
        return cnode

    def edge(old_inp, idx, want):
        """New (node, idx) edge for an old input, cast to ``want`` when
        the carried value is float and differs."""
        new_inp = mapped[id(old_inp)]
        have = tags.get((id(new_inp), idx))
        if want is None or have is None or not _is_float(have) \
                or have == want:
            return (new_inp, idx)
        return (cast_to(new_inp, idx, want), 0)

    for node in sym._topo_nodes():
        if node.is_variable:
            mapped[id(node)] = node  # master weights untouched
            tags[(id(node), 0)] = var_dtypes.get(node.name, f32)
            continue
        wants_f32 = (node.op in FP32_ISLAND_OPS or _untouchable(node.op)
                     or node.name in excluded)
        # an existing Cast converts whatever arrives — forcing its
        # input back to f32 would just stack a redundant cast (and
        # break idempotence); leave its edges alone
        want = None if node.op == "Cast" else f32 if wants_f32 else target
        new_inputs = [edge(inp, idx, want) for inp, idx in node.inputs]
        if all(ni is oi and nx == ox for (ni, nx), (oi, ox)
               in zip(new_inputs, node.inputs)):
            new_node = node  # nothing converted upstream: reuse as-is
        else:
            new_node = _Node(node.op, node.name, node.attrs, new_inputs,
                             node.num_outputs, node.attr_dict)
        mapped[id(node)] = new_node
        out_tag = f32 if wants_f32 else target
        if node.op == "Cast":
            try:
                out_tag = _np_dtype(dict(node.attrs).get("dtype"))
            except Exception:
                out_tag = None
        for i in range(node.num_outputs):
            tags[(id(new_node), i)] = out_tag

    # optimizer/metric-visible heads stay f32
    outputs = []
    for hn, hidx in sym._outputs:
        new_hn = mapped[id(hn)]
        have = tags.get((id(new_hn), hidx))
        if have is not None and _is_float(have) and have != f32:
            outputs.append((cast_to(new_hn, hidx, f32), 0))
        else:
            outputs.append((new_hn, hidx))

    if not changed[0]:
        return sym
    return Symbol(outputs)


class AMPPass(Pass):
    """Pass-manager wrapper; reads ``target_dtype`` / ``excluded`` from
    ``ctx.options`` (defaults: bfloat16, none)."""

    name = "amp"

    def __init__(self, target_dtype="bfloat16", excluded=()):
        self.target_dtype = target_dtype
        self.excluded = tuple(excluded)

    def run(self, sym, ctx):
        return _amp_impl(
            sym,
            target_dtype=ctx.options.get("amp_target_dtype",
                                         self.target_dtype),
            excluded=tuple(ctx.options.get("amp_excluded", self.excluded)),
            input_dtypes=ctx.input_dtypes)


def amp_convert(sym, target_dtype="bfloat16", excluded=(),
                input_shapes=None, input_dtypes=None, ctx=None):
    """Convert ``sym`` to mixed precision, verified by the pass manager.

    ``input_shapes``/``input_dtypes`` seed the post-pass verifier (and
    the integer-input detection); pass a full set for exact dtype-level
    verification of the converted graph.
    """
    ctx = ctx or PassContext(input_shapes=input_shapes,
                             input_dtypes=input_dtypes)
    return AMPPass(target_dtype=target_dtype, excluded=excluded)(sym, ctx)
