"""Autogenerate ``sym.*`` op functions (reference: python/mxnet/symbol/
register.py — one function per registered op, building graph nodes)."""

from __future__ import annotations

from ..ops import registry as _reg
from .symbol import Symbol, _create


def _make_sym_func(op_name):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        kwargs.pop("out", None)
        names = _reg.OP_INPUT_NAMES.get(op_name)
        if op_name == "Custom" and "op_type" in kwargs:
            # a custom op's tensor slots come from its prop, so Symbol
            # kwargs bind BY NAME in the prop's declared order (else
            # dict insertion order would silently miswire inputs)
            from ..ops.custom import _prop_for

            extra = {k: v for k, v in kwargs.items()
                     if k != "op_type" and not isinstance(v, Symbol)}
            names = tuple(_prop_for(kwargs["op_type"],
                                    extra).list_arguments())
        inputs = []
        nones = []  # positions passed as None — resolved by slot name below
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
            elif a is None:
                # absent optional input: legal only when the slot can be
                # identified by name (else later inputs would shift)
                if names is None or len(inputs) >= len(names):
                    raise TypeError(
                        "%s: positional arg %d is None but the input slot "
                        "is unknown" % (op_name, len(inputs)))
                nones.append(names[len(inputs) + len(nones)])
                inputs.append(None)
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], Symbol):
                inputs.extend(a)
            else:
                raise TypeError(
                    "%s: positional args must be Symbols; pass attrs as kwargs"
                    % op_name)
        if names:
            taken = len(inputs)
            for tn in names[taken:]:
                if tn in kwargs and isinstance(kwargs[tn], Symbol):
                    inputs.append(kwargs.pop(tn))
                elif tn in kwargs and kwargs[tn] is None:
                    kwargs.pop(tn)
                elif any(isinstance(v, Symbol) for v in kwargs.values()):
                    continue
        else:
            for k in list(kwargs):
                if isinstance(kwargs[k], Symbol):
                    inputs.append(kwargs.pop(k))
        return _create(op_name, inputs, kwargs, name=name)

    fn.__name__ = op_name
    fn.__qualname__ = op_name
    return fn


def populate(namespace, names=None):
    for name in (names if names is not None else _reg.list_ops()):
        op = _reg.get(name)
        f = _make_sym_func(name)
        namespace[name] = f
        for alias in op.aliases:
            namespace.setdefault(alias, f)
    return namespace
