"""``mx.sym`` — symbolic graph API (reference: python/mxnet/symbol/)."""

from .symbol import Group, Symbol, Variable, load, load_json, var  # noqa: F401
from .register import populate as _populate

_populate(globals())

from . import random  # noqa: E402,F401
from . import contrib  # noqa: E402,F401

from .amp import AMPPass, amp_convert  # noqa: E402,F401
from .passes import (  # noqa: E402,F401
    FunctionPass, Pass, PassContext, PassError, sequential)
from .verify import (  # noqa: E402,F401
    GraphFinding, VerifyResult, assert_valid, verify_graph)

zeros = globals()["_zeros"]
ones = globals()["_ones"]
