"""Graph verifier — a static abstract interpreter over the Symbol DAG.

Every graph rewrite in this repo (subgraph partitioning, int8
quantization, AMP, and whatever lands next) produces a new ``Symbol``
by hand-building ``_Node`` objects.  A single wrong edge — a dangling
output index, an op the registry never heard of, an unhashable attr
that silently drops every call out of the jit cache — survives until
bind time, where it surfaces as an opaque executor trace error (or
worse, as a perf cliff with no error at all).  This module is the
mxlint of the graph IR: it proves a Symbol sound *before* the executor
sees it, and prints the offending node with its path to a graph head.

Checks, in order:

1. **Structural invariants** (no jax needed): acyclicity (own DFS
   coloring — ``Symbol._topo_nodes`` terminates on cycles but returns
   a wrong order, so the verifier cannot reuse it), no dangling input
   refs (``0 <= idx < producer.num_outputs``), variables carry no
   inputs, unique node names, every op registered, arity within the
   exact range ``symbol._create`` can produce for the op's
   ``OP_INPUT_NAMES`` row (mirroring its optional-slot skipping:
   no_bias, use_sequence_length, data/label lengths, LeakyReLU gamma).
2. **Cache-key soundness**: attrs are canonicalized and split
   static-vs-traced exactly as ``registry.Op._split_attrs`` will split
   them at dispatch; the resulting cache key must hash.  An unhashable
   static attr is named — it would demote every call of that node to
   the eager-trace fallback (``apply_op``'s TypeError path), a silent
   perf bug no runtime error ever reports.
3. **Abstract interpretation**: per-node ``jax.eval_shape`` over
   propagated shape/dtype avals — variable shapes seeded through
   ``_infer_param_shapes`` (the same solver ``infer_shape`` uses),
   variable dtypes through ``__dtype__`` attrs, the quantization
   naming contract (``*_quantize`` -> int8, ``*_quantize_min/_max`` ->
   f32 range scalars), and — for registry-table ops — the canonical
   input specs of ``tools/mxlint/registry_audit`` as dtype hints
   (Embedding indices, sequence lengths).  Random ops get the PRNG key
   prepended exactly as the executor prepends it.  A node that fails
   to trace, or traces to a different output count than it declares,
   is a finding; nodes whose input shapes stay unknown are *skipped*
   (partial verification), never guessed.

Zero-false-positive contract (the mxlint tradition): every graph the
public builders produce — symbol API, gluon traces, ``load_json``
round-trips, and both production rewrites — verifies clean.  The
mutation suite (tests/test_graph_verify.py) pins the other side: each
seeded fault is caught with the exact node named.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype as _np_dtype
from ..ops import registry as _reg
from ..ops.registry import OP_INPUT_NAMES
from .symbol import _infer_param_shapes

__all__ = ["GraphFinding", "VerifyResult", "verify_graph", "assert_valid",
           "variable_dtypes"]


class GraphFinding:
    """One invariant violation at one graph node (mxlint-style)."""

    __slots__ = ("rule", "node", "op", "message", "path")

    def __init__(self, rule, node, op, message, path=""):
        self.rule = rule        # short rule id, e.g. "dangling-input"
        self.node = node        # offending node name
        self.op = op            # its op name ("" for variables)
        self.message = message
        self.path = path        # "node -> consumer -> ... -> head"

    def __repr__(self):
        return "GraphFinding(%s, %s)" % (self.rule, self.node)

    def format(self):
        op = (" (op %s)" % self.op) if self.op else ""
        path = (" [path: %s]" % self.path) if self.path else ""
        return "graph:%s: node %r%s: %s%s" % (self.rule, self.node, op,
                                              self.message, path)

    def to_dict(self):
        return {"rule": self.rule, "node": self.node, "op": self.op,
                "message": self.message, "path": self.path}


class VerifyResult:
    """Outcome of :func:`verify_graph`."""

    __slots__ = ("findings", "skipped", "nodes", "evaluated")

    def __init__(self, findings, skipped, nodes, evaluated):
        self.findings = findings    # list of GraphFinding
        self.skipped = skipped      # node names with unknown input shapes
        self.nodes = nodes          # total nodes inspected
        self.evaluated = evaluated  # op nodes traced under eval_shape

    @property
    def ok(self):
        return not self.findings

    def format(self):
        lines = [f.format() for f in self.findings]
        lines.append("graph verify: %d finding(s) over %d node(s) "
                     "(%d traced, %d skipped for unknown shapes)"
                     % (len(self.findings), self.nodes, self.evaluated,
                        len(self.skipped)))
        return "\n".join(lines)


# ------------------------------------------------------------ traversal


def _collect(sym):
    """Own DFS (white/gray/black coloring): returns ``(order, nodes,
    back_edges)``.  ``order`` is a valid evaluation order iff
    ``back_edges`` is empty; ``_topo_nodes`` cannot be reused here
    because its seen-set makes it terminate on cycles with a silently
    wrong order."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {}
    order = []
    nodes = {}
    back_edges = []
    for root, _ in sym._outputs:
        if color.get(id(root), WHITE) == BLACK:
            continue
        stack = [(root, 0)]
        while stack:
            node, i = stack.pop()
            if i == 0:
                if color.get(id(node), WHITE) != WHITE:
                    continue  # duplicate stack entry
                color[id(node)] = GRAY
                nodes[id(node)] = node
            if i < len(node.inputs):
                stack.append((node, i + 1))
                child = node.inputs[i][0]
                c = color.get(id(child), WHITE)
                if c == GRAY:
                    # child is discovered-but-unfinished = on the
                    # current DFS path: a genuine back edge
                    back_edges.append((node, child))
                elif c == WHITE:
                    stack.append((child, 0))
            else:
                color[id(node)] = BLACK
                order.append(node)
    return order, nodes, back_edges


def _consumers(order):
    out = {}
    for n in order:
        for inp, _ in n.inputs:
            out.setdefault(id(inp), []).append(n)
    return out


def _path_to_head(sym, node, consumers, limit=12):
    """Render ``node -> consumer -> ... -> head`` (BFS shortest)."""
    head_ids = {}
    for i, (hn, _) in enumerate(sym._outputs):
        head_ids.setdefault(id(hn), i)
    seen = {id(node)}
    frontier = [(node, [node])]
    while frontier:
        cur, path = frontier.pop(0)
        if id(cur) in head_ids:
            names = [p.name for p in path[:limit]]
            if len(path) > limit:
                names.append("...")
            return " -> ".join(names) + \
                " [output %d]" % head_ids[id(cur)]
        for nxt in consumers.get(id(cur), ()):
            if id(nxt) not in seen:
                seen.add(id(nxt))
                frontier.append((nxt, path + [nxt]))
    return node.name + " (unreachable from any output)"


# ------------------------------------------------------- dtype seeding


def _spec_dtype_hints(order, dtypes):
    """Non-float dtype hints for unseeded variables from the registry
    canonical specs (tools/mxlint/registry_audit) — an Embedding's
    ``data`` slot is int32 by spec, so the verifier must not assume
    f32 for the variable feeding it.  Best-effort: when the tools
    package is not importable (installed-package use), no hints."""
    try:
        from tools.mxlint.registry_audit import canonical_spec
    except ImportError:  # pragma: no cover - repo layout always has it
        return
    for node in order:
        if node.op is None:
            continue
        spec = canonical_spec(node.op)
        if spec is None:
            continue
        input_specs, _attrs = spec
        for i, (inp, _idx) in enumerate(node.inputs):
            if i >= len(input_specs) or not inp.is_variable:
                continue
            if inp.name in dtypes:
                continue
            d = _np.dtype(input_specs[i][1])
            if d != _np.float32:
                dtypes[inp.name] = d


def variable_dtypes(sym, input_dtypes=None, default=_np.float32):
    """{variable name: numpy dtype} for every variable in ``sym``.

    Precedence: explicit ``input_dtypes`` > the variable's
    ``__dtype__`` attr > the quantization naming contract
    (``*_quantize`` -> int8, ``*_quantize_min/_max`` -> f32 scalars,
    mirroring ``Symbol.infer_type``) > non-float canonical-spec slot
    hints > ``default``.  Shared with the AMP pass, which must know an
    integer input when it sees one (indices are never cast to bf16).
    """
    order, _nodes, back = _collect(sym)
    dtypes = {}
    for node in order:
        if not node.is_variable:
            continue
        name = node.name
        if input_dtypes and name in input_dtypes:
            dtypes[name] = _np_dtype(input_dtypes[name])
        elif "__dtype__" in node.attr_dict:
            try:
                dtypes[name] = _np_dtype(node.attr_dict["__dtype__"])
            except (TypeError, MXNetError):
                pass
        elif name.endswith("_quantize"):
            dtypes[name] = _np.dtype(_np.int8)
        elif name.endswith(("_quantize_min", "_quantize_max")):
            dtypes[name] = _np.dtype(_np.float32)
    if not back:
        _spec_dtype_hints(order, dtypes)
    for node in order:
        if node.is_variable:
            dtypes.setdefault(node.name, _np.dtype(default))
    return dtypes


# ---------------------------------------------------------- the checks


def _default_no_bias(op_obj):
    """The op fn's signature default for no_bias (mirrors _create)."""
    import inspect

    try:
        p = inspect.signature(op_obj.fn).parameters.get("no_bias")
        if p is not None and p.default is not inspect.Parameter.empty:
            return bool(p.default)
    except (TypeError, ValueError):
        pass
    return False


def _arity_range(op_name, op_obj, attrs):
    """``(lo, hi)`` input counts ``symbol._create`` can produce for a
    table op under these attrs, or None for non-table (variadic) ops."""
    names = OP_INPUT_NAMES.get(op_name)
    if not names:
        return None
    hi = len(names)
    lo = hi
    no_bias = attrs.get("no_bias", _default_no_bias(op_obj))
    use_seq = attrs.get("use_sequence_length", False)
    for iname in names:
        if iname == "bias" and no_bias:
            lo -= 1
        elif iname == "sequence_length" and not use_seq:
            lo -= 1
        elif iname in ("data_lengths", "label_lengths"):
            lo -= 1
        elif iname == "gamma" and op_name == "LeakyReLU" \
                and attrs.get("act_type", "leaky") != "prelu":
            lo -= 1
    return lo, hi


def _unhashable_attr(attrs):
    """Name of the first attr whose canonical value does not hash."""
    for k in sorted(attrs):
        try:
            hash(attrs[k])
        except TypeError:
            return k
    return None


def _random_op_names():
    from ..ndarray.ndarray import RANDOM_OPS

    return set(RANDOM_OPS) | {"Dropout"}


def verify_graph(sym, input_shapes=None, input_dtypes=None):
    """Verify a Symbol DAG; returns a :class:`VerifyResult`.

    ``input_shapes`` / ``input_dtypes``: {variable name: shape/dtype}
    seeds for the abstract interpretation — without them structural and
    cache-key checks still run in full, and nodes whose shapes stay
    unknown are reported in ``result.skipped`` instead of guessed.
    """
    order, nodes, back_edges = _collect(sym)
    consumers = _consumers(order)
    findings = []

    def find(rule, node, message):
        findings.append(GraphFinding(
            rule, node.name, node.op or "", message,
            _path_to_head(sym, node, consumers)))

    # ---- acyclicity (everything downstream assumes a DAG)
    for node, child in back_edges:
        find("cycle", node,
             "input edge to %r closes a cycle — the graph is not a DAG"
             % child.name)

    # ---- dangling refs, variable shape, duplicate names
    by_name = {}
    for node in order:
        by_name.setdefault(node.name, []).append(node)
        for inp, idx in node.inputs:
            if not (0 <= idx < inp.num_outputs):
                find("dangling-input", node,
                     "input references output %d of %r, which has only "
                     "%d output(s)" % (idx, inp.name, inp.num_outputs))
        if node.is_variable and node.inputs:
            find("variable-inputs", node,
                 "variable node carries %d input edge(s); variables "
                 "must be leaves" % len(node.inputs))
    for hn, hidx in sym._outputs:
        if not (0 <= hidx < hn.num_outputs):
            find("dangling-output", hn,
                 "graph head references output %d, but the node has "
                 "only %d output(s)" % (hidx, hn.num_outputs))
    for name, dups in sorted(by_name.items()):
        if len(dups) > 1:
            kinds = ", ".join(d.op or "variable" for d in dups)
            find("duplicate-name", dups[1],
                 "name %r is used by %d distinct nodes (%s) — executor "
                 "argument binding and JSON round-trips key by name"
                 % (name, len(dups), kinds))

    # ---- registry presence, arity, cache-key soundness, num_outputs
    canon_attrs = {}  # id(node) -> canonicalized attrs (for eval below)
    for node in order:
        if node.is_variable:
            continue
        op_obj = _reg._OP_REGISTRY.get(node.op)
        if op_obj is None:
            find("unknown-op", node,
                 "op %r is not in the operator registry" % node.op)
            continue
        try:
            canon = op_obj.canonicalize_attrs(node.attrs or {})
        except Exception as e:
            find("attr-canon", node,
                 "canonicalize_attrs failed: %s: %s"
                 % (type(e).__name__, str(e).split("\n")[0]))
            continue
        canon_attrs[id(node)] = canon
        # cache key exactly as dispatch will build it
        try:
            key = op_obj._split_attrs(canon)[0]
        except TypeError:
            key = None
        hashable = True
        if key is not None:
            try:
                hash(key)
            except TypeError:
                hashable = False
        if key is None or not hashable:
            bad = _unhashable_attr(canon)
            find("unhashable-attr", node,
                 "attr %r (%s) is unhashable after canonicalization — "
                 "the jit-cache key cannot be built, so every call of "
                 "this node falls back to eager tracing"
                 % (bad, type(canon.get(bad)).__name__))
            canon_attrs.pop(id(node), None)
            continue
        rng = _arity_range(node.op, op_obj, canon)
        if rng is not None:
            lo, hi = rng
            if not (lo <= len(node.inputs) <= hi):
                find("arity", node,
                     "op %r takes %s input(s) (%s) under these attrs, "
                     "but the node has %d"
                     % (node.op,
                        ("%d" % hi) if lo == hi else "%d..%d" % (lo, hi),
                        ", ".join(OP_INPUT_NAMES[node.op]),
                        len(node.inputs)))
        declared = node.num_outputs
        try:
            nout = op_obj.nout(canon)
        except Exception:
            nout = None
        if nout is not None and nout != declared:
            find("num-outputs", node,
                 "node declares %d output(s) but op %r produces %d "
                 "under these attrs" % (declared, node.op, nout))

    # ---- abstract interpretation (skipped entirely on a cyclic graph)
    skipped = []
    evaluated = 0
    if not back_edges:
        skipped, evaluated = _abstract_interp(
            sym, order, canon_attrs, input_shapes, input_dtypes, find,
            findings)
    return VerifyResult(findings, skipped, len(order), evaluated)


def _abstract_interp(sym, order, canon_attrs, input_shapes, input_dtypes,
                     find, findings):
    import jax

    known = dict(input_shapes or {})
    try:
        shapes = _infer_param_shapes(sym, known)
    except MXNetError as e:
        # a structural contradiction (provided shape vs op semantics)
        # is itself a finding; fall back to the raw seeds so the rest
        # of the graph still gets partial verification
        findings.append(GraphFinding(
            "shape-infer", sym._outputs[0][0].name, "",
            "parameter shape inference failed: %s"
            % str(e).split("\n")[0]))
        shapes = known
    dtypes = variable_dtypes(sym, input_dtypes)
    flagged = {f.node for f in findings}
    random_ops = _random_op_names()
    key_aval = None
    entry = {}  # (id(node), idx) -> ShapeDtypeStruct or None
    skipped = []
    evaluated = 0
    for node in order:
        if node.is_variable:
            s = shapes.get(node.name)
            entry[(id(node), 0)] = None if s is None else \
                jax.ShapeDtypeStruct(tuple(s), dtypes[node.name])
            if s is None:
                skipped.append(node.name)
            continue
        if id(node) not in canon_attrs:
            # unknown op / broken attrs: already a finding; outputs
            # stay unknown downstream
            for i in range(node.num_outputs):
                entry[(id(node), i)] = None
            continue
        avals = [entry.get((id(inp), idx)) for inp, idx in node.inputs]
        if any(a is None for a in avals):
            skipped.append(node.name)
            for i in range(node.num_outputs):
                entry[(id(node), i)] = None
            continue
        canon = canon_attrs[id(node)]
        op_obj = _reg._OP_REGISTRY[node.op]
        fn = op_obj.bind_attrs(canon)
        if node.op in random_ops:
            # the executor prepends a TraceRNG key for these; mirror it
            if key_aval is None:
                k = jax.random.PRNGKey(0)
                key_aval = jax.ShapeDtypeStruct(tuple(k.shape), k.dtype)
            avals = [key_aval] + avals
        try:
            out = jax.eval_shape(fn, *avals)
            evaluated += 1
        except Exception as e:
            find("node-eval", node,
                 "abstract evaluation failed on input avals (%s): "
                 "%s: %s"
                 % (", ".join("%s%s" % (a.dtype, list(a.shape))
                              for a in avals),
                    type(e).__name__, str(e).split("\n")[0][:300]))
            for i in range(node.num_outputs):
                entry[(id(node), i)] = None
            continue
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        if len(outs) != node.num_outputs and node.name not in flagged:
            find("num-outputs", node,
                 "node declares %d output(s) but tracing produced %d"
                 % (node.num_outputs, len(outs)))
        for i in range(node.num_outputs):
            entry[(id(node), i)] = outs[i] if i < len(outs) else None
    return skipped, evaluated


def assert_valid(sym, input_shapes=None, input_dtypes=None, context=""):
    """Raise :class:`MXNetError` listing every finding (with node paths)
    when ``sym`` fails verification; returns the VerifyResult when
    clean.  ``context`` names the producer (e.g. the pass) in the
    error."""
    result = verify_graph(sym, input_shapes=input_shapes,
                          input_dtypes=input_dtypes)
    if not result.ok:
        where = (" after %s" % context) if context else ""
        raise MXNetError("invalid graph%s:\n%s" % (where, result.format()))
    return result
