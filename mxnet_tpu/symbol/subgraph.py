"""Pluggable graph partitioning (reference: src/operator/subgraph/
subgraph_property.h:93 SubgraphProperty + partition_graph.cc:735).

The reference grows subgraphs from seed nodes with a SubgraphSelector,
replaces each region with a subgraph op, and activates backends via
``MXNET_SUBGRAPH_BACKEND``.  This is the same framework over this
repo's Symbol DAG, TPU-first in one way: the default replacement op
(``_subgraph_exec``) stages its region through the jit cache as ONE
compiled callee — the CachedOp-style encapsulation the reference uses
subgraphs for, with XLA doing the actual fusion inside.

API:
  class MySelector(SubgraphSelector): select / select_input / ...
  class MyProperty(SubgraphProperty): create_selector /
      create_subgraph_node
  register_subgraph_property("MY_BACKEND", MyProperty)
  new_sym = partition_graph(sym, "MY_BACKEND")
``Symbol.simple_bind`` honors MXNET_SUBGRAPH_BACKEND.
"""

from __future__ import annotations

from ..base import MXNetError
from .passes import Pass, PassContext
from .symbol import Symbol, Variable, _Node

__all__ = ["SubgraphSelector", "SubgraphProperty",
           "register_subgraph_property", "list_subgraph_properties",
           "partition_graph", "PartitionPass"]

_PROPERTIES: dict[str, type] = {}


class SubgraphSelector:
    """Decides which nodes join a region (reference:
    subgraph_property.h SubgraphSelector).  Traversal starts at a seed
    where ``select`` is true and grows along inputs/outputs gated by
    ``select_input`` / ``select_output``."""

    def select(self, node):
        raise NotImplementedError

    def select_input(self, cur_node, input_node):
        return False

    def select_output(self, cur_node, output_node):
        return False

    def filter(self, candidates):
        """Post-process a grown region; return the nodes to keep."""
        return candidates


class SubgraphProperty:
    """Partitioning policy: selection + replacement
    (reference: subgraph_property.h SubgraphProperty)."""

    def create_selector(self):
        raise NotImplementedError

    def create_subgraph_node(self, subgraph_sym, subgraph_id=0):
        """Return the Symbol replacing a matched region.  Its outputs
        must line up 1:1 with ``subgraph_sym``'s outputs, and its
        arguments must keep the sub-symbol's argument names (they are
        re-wired to the original producers by name).

        Default: wrap the region in one ``_subgraph_exec`` node."""
        return _wrap_subgraph(subgraph_sym, subgraph_id)


def register_subgraph_property(name, prop_cls):
    """Register under the MXNET_SUBGRAPH_BACKEND name
    (reference: MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    if not isinstance(name, str) or not name:
        raise MXNetError("subgraph property name must be a non-empty str")
    _PROPERTIES[name] = prop_cls
    return prop_cls


def list_subgraph_properties():
    return sorted(_PROPERTIES)


def _get_property(prop):
    if isinstance(prop, SubgraphProperty):
        return prop
    if isinstance(prop, str):
        try:
            return _PROPERTIES[prop]()
        except KeyError:
            raise MXNetError(
                "unknown subgraph backend %r (registered: %s)"
                % (prop, ", ".join(list_subgraph_properties()) or "none"))
    if isinstance(prop, type):
        return prop()
    raise MXNetError("expected property name/class/instance, got %r"
                     % (prop,))


# ---------------------------------------------------------------- wrapping --
def _wrap_subgraph(sub_sym, subgraph_id):
    """Default replacement: one ``_subgraph_exec`` node carrying the
    region as JSON; evaluation stages the region through the jit cache
    as a single compiled callee.  Node inputs are ALL leaf variables in
    ``list_inputs()`` order — the order _subgraph_exec rebinds by."""
    from . import symbol as sym_api

    variables = [Variable(n) for n in sub_sym.list_inputs()]
    json_str = sub_sym.tojson()
    return sym_api._create(
        "_subgraph_exec", variables,
        {"subgraph_json": json_str, "num_outputs": len(sub_sym._outputs)},
        name="subgraph%d" % subgraph_id)


# ------------------------------------------------------------- partitioning --
def _capturable(node):
    """The default machinery captures PURE ops only: no PRNG consumers,
    no auxiliary state (BatchNorm moving stats and friends) — a
    captured region must be correct regardless of train/eval mode and
    must not need aux plumbing.  Properties wanting stateful capture
    own that complexity in a custom create_subgraph_node."""
    from ..ndarray.ndarray import RANDOM_OPS
    from ..ops.registry import OP_AUX_INPUTS

    return (not node.is_variable and node.op not in RANDOM_OPS
            and node.op not in OP_AUX_INPUTS and node.op != "Dropout")


def _grow_region(seed, selector, consumers, claimed):
    """Grow one candidate region from `seed` by the selector's rules."""
    region = {id(seed): seed}
    frontier = [seed]
    while frontier:
        cur = frontier.pop()
        for inp, _ in cur.inputs:
            if inp.is_variable or id(inp) in region or id(inp) in claimed \
                    or not _capturable(inp):
                continue
            if selector.select_input(cur, inp):
                region[id(inp)] = inp
                frontier.append(inp)
        for out in consumers.get(id(cur), ()):
            if id(out) in region or id(out) in claimed \
                    or not _capturable(out):
                continue
            if selector.select_output(cur, out):
                region[id(out)] = out
                frontier.append(out)
    kept = selector.filter(list(region.values()))
    return {id(n): n for n in kept}


def _region_is_convex(region):
    """A region is splice-able iff no path leaves it and re-enters: with
    nodes in topo order, every external input of a region node must come
    before every region node that feeds an external consumer... the
    cheap sufficient check: for each region node, every non-region
    ancestor on a path from another region node would violate order.
    We check directly: no region node has a non-region ancestor that
    itself has a region ancestor."""
    region_ids = set(region)
    # compute, for each node in the induced ancestor cone, whether it
    # has a region ancestor
    memo = {}

    def has_region_anc(node):
        if id(node) in memo:
            return memo[id(node)]
        memo[id(node)] = False  # cycle-safe default (DAG anyway)
        res = False
        for inp, _ in node.inputs:
            if id(inp) in region_ids or has_region_anc(inp):
                res = True
                break
        memo[id(node)] = res
        return res

    for n in region.values():
        for inp, _ in n.inputs:
            if id(inp) in region_ids:
                continue
            if has_region_anc(inp):
                return False
    return True


def _extract_subgraph(region, topo):
    """Clone a region into a standalone DAG with named placeholders.

    Returns ``(ordered, clones, ext_inputs, placeholder_names)`` where
    ``ext_inputs`` are the original external (node, idx) entries and
    ``placeholder_names[i]`` is the Variable name standing in for
    ``ext_inputs[i]`` — rewiring binds BY NAME, never by position."""
    region_ids = set(region)
    ext_inputs = []  # original entries, deduped in first-seen order
    ext_names = []
    ext_index = {}
    clones = {}
    placeholder = {}

    def entry_to_clone(inp, idx):
        if id(inp) in region_ids:
            return (clones[id(inp)], idx)
        key = (id(inp), idx)
        if key not in ext_index:
            ext_index[key] = len(ext_inputs)
            name = inp.name if inp.is_variable and idx == 0 else \
                "%s_out%d" % (inp.name, idx)
            pname = "_sg_in%d_%s" % (len(ext_inputs), name)
            placeholder[key] = Variable(pname)._outputs[0][0]
            ext_inputs.append((inp, idx))
            ext_names.append(pname)
        return (placeholder[key], 0)

    ordered = [n for n in topo if id(n) in region_ids]
    for node in ordered:
        new_inputs = [entry_to_clone(inp, idx) for inp, idx in node.inputs]
        clones[id(node)] = _Node(node.op, node.name, node.attrs, new_inputs,
                                 node.num_outputs, dict(node.attr_dict))
    return ordered, clones, ext_inputs, ext_names


def _partition_impl(sym, prop):
    """The partitioning rewrite itself (reference: partition_graph.cc
    PartitionGraph).  Returns a new Symbol — or ``sym`` itself when no
    region matches; the input is untouched either way.  Public entry is
    :func:`partition_graph`, which routes through the pass manager."""
    prop = _get_property(prop)
    topo = sym._topo_nodes()

    consumers = {}
    for n in topo:
        for inp, _ in n.inputs:
            consumers.setdefault(id(inp), []).append(n)

    # ---- select regions
    claimed = set()
    regions = []
    for node in topo:
        if node.is_variable or id(node) in claimed or not _capturable(node):
            continue
        selector = prop.create_selector()
        if not selector.select(node):
            continue
        region = _grow_region(node, selector, consumers, claimed)
        if not region or not _region_is_convex(region):
            continue
        claimed.update(region)
        regions.append(region)
    if not regions:
        return sym

    node_region = {}
    for rid, region in enumerate(regions):
        for nid in region:
            node_region[nid] = rid
    # a region is spliced in when its LAST member is reached, so every
    # external input (all of which precede that point in topo order)
    # is already mapped
    last_member = {}
    for i, n in enumerate(topo):
        rid = node_region.get(id(n))
        if rid is not None:
            last_member[rid] = id(n)

    # ---- rebuild the graph, splicing replacements in
    entry_map = {}  # (id(old node), idx) -> (new node, idx)

    def mapped(inp, idx):
        if inp.is_variable:
            return entry_map.setdefault(
                (id(inp), idx),
                (_Node(None, inp.name, {}, [], 1, dict(inp.attr_dict)), 0))
        return entry_map[(id(inp), idx)]

    def emit_region(rid):
        region = regions[rid]
        ordered, clones, ext_inputs, ext_names = _extract_subgraph(
            region, topo)
        # region outputs: entries used outside the region or as heads
        out_entries = []
        for n in ordered:
            external = any(id(c) not in region
                           for c in consumers.get(id(n), ()))
            for idx in range(n.num_outputs):
                is_head = any(hn is n and hidx == idx
                              for hn, hidx in sym._outputs)
                if external or is_head:
                    out_entries.append((n, idx))
        sub_sym = Symbol([(clones[id(n)], idx) for n, idx in out_entries])
        replacement = prop.create_subgraph_node(sub_sym, rid)
        if len(replacement._outputs) != len(out_entries):
            raise MXNetError(
                "subgraph property returned %d outputs for a region "
                "with %d" % (len(replacement._outputs), len(out_entries)))
        # rewire the replacement's placeholder variables BY NAME
        arg_map = {pname: mapped(inp, idx)
                   for pname, (inp, idx) in zip(ext_names, ext_inputs)}
        _rewire_arguments(replacement, arg_map)
        for k, (n, idx) in enumerate(out_entries):
            entry_map[(id(n), idx)] = replacement._outputs[k]

    for node in topo:
        if node.is_variable:
            mapped(node, 0)
            continue
        rid = node_region.get(id(node))
        if rid is not None:
            if last_member[rid] == id(node):
                emit_region(rid)
            continue
        new_inputs = [mapped(inp, idx) for inp, idx in node.inputs]
        new_node = _Node(node.op, node.name, node.attrs, new_inputs,
                         node.num_outputs, dict(node.attr_dict))
        for idx in range(node.num_outputs):
            entry_map[(id(node), idx)] = (new_node, idx)

    return Symbol([entry_map[(id(n), idx)] for n, idx in sym._outputs])


class PartitionPass(Pass):
    """Pass-manager wrapper around :func:`_partition_impl`: the rewrite
    is unchanged, but its output is re-verified before anyone binds it
    and its node/cost delta lands in runtime_stats' ``graph_passes``."""

    def __init__(self, prop):
        self._prop = prop
        label = prop if isinstance(prop, str) else \
            getattr(prop, "__name__", type(prop).__name__)
        self.name = "partition:%s" % label

    def run(self, sym, ctx):
        return _partition_impl(sym, self._prop)


def partition_graph(sym, prop, ctx=None):
    """Replace every region the property selects (reference:
    partition_graph.cc PartitionGraph).  Returns a new, verified Symbol
    — or ``sym`` itself when no region matches (callers like
    ``simple_bind`` test ``part is not self``)."""
    return PartitionPass(prop)(sym, ctx or PassContext())


def _rewire_arguments(replacement, arg_map):
    """Point the replacement symbol's named variable leaves at mapped
    original entries."""
    for node in replacement._topo_nodes():
        new_inputs = []
        for inp, idx in node.inputs:
            if inp.is_variable and inp.name in arg_map:
                new_inputs.append(arg_map[inp.name])
            else:
                new_inputs.append((inp, idx))
        node.inputs = new_inputs
