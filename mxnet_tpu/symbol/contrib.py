"""sym.contrib — contrib op namespace for symbols.

Reference: python/mxnet/symbol/contrib.py.  The op set mirrors
nd.contrib (ndarray/contrib.py); symbolic control flow (foreach /
while_loop / cond) builds the corresponding graph nodes when the
executor traces the graph — on this framework symbols execute by
tracing into XLA, so the nd implementations are reused at bind time.
"""

from __future__ import annotations

from ..ops import registry as _reg
from .register import populate as _populate

_CONTRIB_OPS = [
    "box_nms", "box_iou", "MultiBoxPrior", "MultiBoxTarget",
    "MultiBoxDetection", "ROIAlign", "BilinearResize2D",
    "AdaptiveAvgPooling2D", "boolean_mask", "quadratic",
    "arange_like", "getnnz", "index_copy", "index_add",
    "adamw_update", "_contrib_flash_attention", "_contrib_div_sqrt_dim",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
]

_populate(globals(), names=[n for n in _CONTRIB_OPS if n in _reg.list_ops()])
