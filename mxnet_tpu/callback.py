"""Training callbacks.

API parity with the reference's ``python/mxnet/callback.py``
(Speedometer, do_checkpoint, module_checkpoint, log_train_metric,
ProgressBar, LogValidationMetricsCallback), reimplemented in this
repo's own idiom.  The *log line formats* are deliberately kept
reference-identical — "Epoch[%d] Batch [%d]\\tSpeed: ..." and
"Validation-%s=%f" are parsed by tools/parse_log.py and by a decade of
user grep scripts, so they are part of the observable API surface.
"""

from __future__ import annotations

import logging
import math
import sys
import time


def _every(period):
    """Normalize an epoch/batch period to a positive int."""
    return max(1, int(period))


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback saving a Module checkpoint every `period`."""
    period = _every(period)

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving symbol+params every `period` epochs."""
    from .model import save_checkpoint

    period = _every(period)

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the training metric every `period`."""
    period = _every(period)

    def _callback(param):
        if param.nbatch % period or param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset:
            param.eval_metric.reset()

    return _callback


class Speedometer:
    """Batch-end callback logging throughput (and metrics) every
    `frequent` batches.

    Speed is measured over the actual window since the previous report
    (the reference assumes the window is exactly `frequent` batches;
    measuring the real batch count is a conscious, more accurate
    divergence — the log format is unchanged).
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = _every(frequent)
        self.auto_reset = auto_reset
        self._window_start = None  # (monotonic time, nbatch) of last mark

    def _restart(self, param):
        self._window_start = (time.monotonic(), param.nbatch)

    def __call__(self, param):
        mark = self._window_start
        # <= catches a new epoch whose nbatch restarts at the mark's own
        # value (e.g. both 0), not just strictly below it
        if mark is None or param.nbatch <= mark[1]:
            self._restart(param)
            return
        if param.nbatch % self.frequent:
            return
        elapsed = time.monotonic() - mark[0]
        batches = param.nbatch - mark[1]
        speed = (batches * self.batch_size / elapsed) if elapsed > 0 \
            else float("inf")
        metric = param.eval_metric
        if metric is None:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, param.nbatch, speed)
        else:
            pairs = metric.get_name_value()
            if self.auto_reset:
                metric.reset()
            fmt = ("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                   + "\t%s=%f" * len(pairs))
            flat = [x for pair in pairs for x in pair]
            logging.info(fmt, param.epoch, param.nbatch, speed, *flat)
        self._restart(param)


class ProgressBar:
    """Batch-end callback drawing an in-place ASCII bar."""

    def __init__(self, total, length=80):
        self.total = total
        self.bar_len = length

    def __call__(self, param):
        frac = param.nbatch / float(self.total)
        fill = int(round(self.bar_len * frac))
        pct = math.ceil(100.0 * frac)
        bar = "=" * fill + "-" * (self.bar_len - fill)
        sys.stdout.write("[%s] %s%%\r" % (bar, pct))


class LogValidationMetricsCallback:
    """Epoch-end callback logging every validation metric."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
