"""Perf doctor — ranked bottleneck findings from a trace + diag dump.

The observability layers (PR 2/3/5/7) record what happened; this module
*interprets* it: given a chrome trace (``MXNET_TPU_PROFILE``) and/or a
diag dump (``MXNET_TPU_DIAG``), :func:`diagnose` returns findings
**ranked by estimated share of step time**, each naming the concrete
span/op/shard it indicts and a concrete next action — the
measure-compare-decide loop the autotune roadmap item needs (TVM,
arXiv:1802.04799) and the fusion/idle-gap lens of XLA perf work
(arXiv:2301.13062), automated so every perf PR ships with a verdict
instead of a hand-read trace.

Rules
-----
- **step-anatomy shares** — a phase (data wait, allreduce/kvstore,
  optimizer update, checkpoint snapshot) eating an outsized share of
  the per-step wall time (``stepstats`` section of the dump).
- **recompile storms** — ops compiling past the storm threshold, with
  the churned attr/aval evidence from ``recent_storm_keys`` and the
  compile share of step time.
- **eager dispatch tax** — warm per-op dispatch (+ compile) dominating
  an eager run's step time: recommends the compiled whole-step path
  (``MXNET_TPU_COMPILED_STEP`` / ``trainer.compile``) with projected
  savings derived from the warm-dispatch counters.
- **host-sync stalls** — monitor/health host-sync seconds on the hot
  path (the deliberate sync sinks, when their cost stops being small).
- **idle gaps inside steps** — wall time inside ``trainer:step`` spans
  covered by NO recorded span (untracked host work or device waits),
  from the chrome trace.
- **roofline headroom** — the top profiled ops whose cache-warm
  dispatch time sits far above their cost-model roofline bound.
- **kvstore stragglers** — one PS shard's push/pull RTT p99 an outlier
  vs the other shards' median (``histogram.median_of_others``).
- **serving** — ``serve-queue-dominated`` (queue-wait p99 past
  ``SERVE_QUEUE_RATIO`` x the batch-compute p99: this replica is past
  capacity) and ``serve-bucket-churn`` (bucket executables rebuilt past
  the one-per-bucket warmup) from an ``InferenceServer`` run's dump.
- **kvstore self-healing** — dead-shard heartbeat warnings
  (``kvstore_dead_shard_warnings``: a PS shard went unresponsive past
  ``MXNET_TPU_KV_DEADLINE``) and server-side duplicate suppression
  (``kvstore_dup_suppressed`` on a server's dump: retried mutations
  were acked from the exactly-once table instead of re-applying — the
  fingerprint of reply loss / restart drills).
- **fused-step x-ray** (PR 15, ``xray`` section of the dump) —
  ``xray-scope-dominated`` (one Gluon block's fwd+bwd scopes carry
  most of the fused program's flops/bytes, named by path),
  ``xray-zero-collective-share`` (collective vs compute bytes inside
  the ZeRO program, docs/ZERO.md "When not to shard") and
  ``xray-optimizer-share`` (the fused update region's bytes dominate:
  state-dtype/sharding check).

Trend rules (PR 10) run over a **timeline** — the per-step time series
``metrics_timeline`` records (its live ring, a ``MXNET_TPU_METRICS``
JSONL file, or the ``timeline`` section of a diag dump):

- **timeline-leak** — monotonic live-device-bytes growth past a slope
  threshold: the signature of retained NDArrays / autograd graphs that
  OOMs a long run at step 400k, invisible to any single snapshot.
- **timeline-throughput** — the recent window's mean step wall time vs
  the early window's: sustained decay (fragmentation, queue buildup,
  input starvation), with the fastest-growing phase named when the
  samples carry a stepstats breakdown.
- **timeline-spikes** — step-time spikes vs the series median, with
  periodicity detection (a spike every N steps is a cadence —
  checkpoint, eval, logging) and the offending phase named.
- **timeline-kv-drift** — one kv push/pull-RTT series' windowed p99
  drifting up over the run, per shard: the *emerging* straggler the
  end-of-run skew report only catches after the damage.

Findings are ``{"rule", "severity": "warn"|"info", "score",
"title", "anchor", "evidence": [...], "action"}`` — ``score`` is the
estimated fraction of step time at stake (what the ranking sorts by),
``anchor`` the span/op/rank/shard name the evidence points at.

CLI: ``python tools/diagnose.py --doctor <trace.json|diag.json ...>``
(``--format github`` emits ``::error``/``::notice`` workflow
annotations, the mxlint convention).
"""

from __future__ import annotations

from . import histogram as _histogram
from . import runtime_stats as _rts
from . import slo as _slo
from . import stepstats as _stepstats

__all__ = ["diagnose", "classify", "render", "render_github",
           "gh_annotation", "live_dump", "live_findings",
           "SHARE_NOTICE", "SHARE_WARN",
           "HEADROOM_RATIO", "IDLE_GAP_SHARE", "TREND_MIN_SAMPLES",
           "TREND_SLOWDOWN", "LEAK_SLOPE_BYTES", "SPIKE_RATIO",
           "KV_DRIFT_RATIO", "SERVE_QUEUE_RATIO", "SERVE_MIN_REQUESTS",
           "XRAY_DOMINANT_SHARE", "XRAY_ZERO_COLL_SHARE",
           "XRAY_OPT_SHARE"]

# a phase/rule at or above this share of step time is worth a line /
# a warning; tunable per call via diagnose(..., notice=, warn=)
SHARE_NOTICE = 0.10
SHARE_WARN = 0.25
# host-sync sinks are meant to be cheap: flag earlier
SYNC_SHARE_NOTICE = 0.05
# an op is "far off its roofline" when headroom exceeds this fraction
# of its dispatch time AND it carries a meaningful share of total time
HEADROOM_RATIO = 0.5
# untracked time inside trainer:step spans worth flagging
IDLE_GAP_SHARE = 0.20

# ---- trend-rule knobs (timeline series) --------------------------------
# samples below this leave every trend rule silent (too little signal)
TREND_MIN_SAMPLES = 8
# late-window mean step wall must exceed the early window's by this
# fraction before the throughput rule fires (0.5 = 50% slower)
TREND_SLOWDOWN = 0.5
# live-bytes leak: regression slope past this many bytes/step AND total
# growth past LEAK_MIN_GROWTH AND mostly-nondecreasing deltas
LEAK_SLOPE_BYTES = 4096.0
LEAK_MIN_GROWTH = 1 << 20
LEAK_MONOTONIC_FRAC = 0.6
# step-time spikes: > SPIKE_RATIO x the series median, at least
# SPIKE_MIN_COUNT of them past the warmup tail, carrying at least
# SPIKE_MIN_SHARE of the windowed wall time
SPIKE_RATIO = 4.0
SPIKE_MIN_COUNT = 2
SPIKE_WARMUP = 3
SPIKE_MIN_SHARE = 0.10
# a kv-RTT series' late-window mean p99 / early-window mean p99 past
# this ratio is drift
KV_DRIFT_RATIO = 2.0

# ---- serving-rule knobs (InferenceServer dumps) ------------------------
# queue-wait p99 past this multiple of the batch-compute p99 means the
# server is queue-dominated: requests wait longer than they compute
SERVE_QUEUE_RATIO = 2.0
# served requests below this leave the serving rules silent (a handful
# of warmup requests carries no operating-point signal)
SERVE_MIN_REQUESTS = 32

# ---- ZeRO-sharding knobs (parallel/gluon_step.py zero=True runs) -------
# the per-step parameter all-gather past this fraction of the compiled
# step's total bytes-accessed means collectives dominate the traffic the
# sharding saves in state — the model is too small (or the per-device
# batch too thin) for the current dp width
ZERO_AG_RATIO = 0.5

# ---- fused-step x-ray knobs (xray.py per-scope tables) -----------------
# one block scope at or above this share of the whole program's flops
# OR bytes dominates the fused step — name it so the next perf PR
# knows where to aim; warns when it crosses XRAY_DOMINANT_WARN
XRAY_DOMINANT_SHARE = 0.5
XRAY_DOMINANT_WARN = 0.75
# collective traffic inside the ZeRO program past this fraction of the
# forward+backward scopes' bytes (the compute the gather feeds) means
# the sharding's data movement rivals the math — the in-program cousin
# of ZERO_AG_RATIO
XRAY_ZERO_COLL_SHARE = 0.5
# the fused optimizer-update region moving more than this fraction of
# program bytes means the step is state-bound, not math-bound
XRAY_OPT_SHARE = 0.4


def classify(path):
    """Load ``path`` and say what it is: ``("trace", data)`` for a
    chrome trace, ``("dump", data)`` for a diag dump / snapshot, or
    ``("timeline", {"samples": [...]})`` for a metrics-timeline source
    (``MXNET_TPU_METRICS`` JSONL — even a one-line file — or a bare
    JSON sample array).  A file that is neither JSON nor JSONL raises
    ``ValueError`` — a corrupt input must never read as a finding-free
    clean run."""
    from . import metrics_timeline as _mt

    with open(path) as f:
        text = f.read()
    kind, data = _mt.sniff_text(text, path=path)
    if kind != "trace":
        data.setdefault("_path", path)
    return kind, data


def _finding(rule, score, title, anchor, evidence, action,
             warn_at=SHARE_WARN):
    return {"rule": rule, "score": float(score),
            "severity": "warn" if score >= warn_at else "info",
            "title": title, "anchor": anchor,
            "evidence": list(evidence), "action": action}


# ------------------------------------------------------------ dump rules


def _anatomy_of(dump):
    snap = dump.get("snapshot", dump)
    return _stepstats.anatomy(snap.get("stepstats") or {})


def _check_step_anatomy(dump):
    """Phase-share findings: the phases an operator can act on
    directly (data wait / kvstore / optimizer / checkpoint /
    unattributed remainder)."""
    a = _anatomy_of(dump)
    if not a.get("steps"):
        return []
    actions = {
        "data_wait": "overlap input with compute (PrefetchingIter / "
                     "wider io workers) or cache preprocessing "
                     "(docs/OBSERVABILITY.md 'Step anatomy')",
        "kvstore": "check shard placement and gradient sizes; compare "
                   "push/pull RTT histograms per shard (--cluster for "
                   "multi-rank runs)",
        "optimizer_update": "fuse the update (update_on_kvstore or the "
                            "multi-tensor optimizer ops) or batch "
                            "small parameters",
        "checkpoint_write": "raise MXNET_TPU_CKPT_INTERVAL or keep "
                            "MXNET_TPU_CKPT_ASYNC=1 (the capture "
                            "should be microseconds; a large share "
                            "means sync mode or host-resident params)",
        "unattributed": "wall time no instrumented phase covers: "
                        "profile with MXNET_TPU_PROFILE and look for "
                        "host syncs / untracked user code between "
                        "spans (tools/mxlint host-sync-reachability)",
    }
    out = []
    for phase, action in actions.items():
        d = a["phases"].get(phase) if phase != "unattributed" \
            else a.get("unattributed")
        if not d or d["share"] < SHARE_NOTICE:
            continue
        out.append(_finding(
            "step-anatomy", d["share"],
            "%s is %.0f%% of step time"
            % (_stepstats.PHASE_LABELS.get(phase, phase),
               d["share"] * 100),
            phase,
            ["per-step mean %.3f ms (p99 %.3f ms) over %d step(s); "
             "step wall mean %.3f ms"
             % (d["mean_ms"] or 0, d["p99_ms"] or 0, a["steps"],
                a["step_wall_ms"]["mean_ms"] or 0)],
            action))
    return out


def _check_recompiles(dump):
    """Recompile storms: per-op compile counts past the storm
    threshold, scored by the compile phase's share of step time."""
    snap = dump.get("snapshot", dump)
    storms = snap.get("storms") or {}
    threshold = _rts.STORM_THRESHOLD or 8
    hot = {name: st for name, st in storms.items()
           if st.get("compiles", 0) > threshold
           or st.get("distinct_avals", 0) > threshold}
    if not hot:
        return []
    a = _anatomy_of(dump)
    compile_share = (a.get("phases", {}).get("compile") or
                     {}).get("share")
    if compile_share is None:
        # no anatomy in the dump: fall back to compile seconds vs
        # profiled dispatch+compile time (coarse, but still ranks)
        totals = snap.get("totals") or {}
        denom = (totals.get("dispatch_seconds") or 0.0) \
            + (totals.get("compile_seconds") or 0.0)
        compile_share = (totals.get("compile_seconds", 0.0) / denom) \
            if denom else 0.5
    worst = max(hot, key=lambda n: hot[n].get("compiles", 0))
    keys = (dump.get("recent_storm_keys") or {}).get(worst) or []
    evidence = ["%s: %d compile(s), %d distinct input signature(s)"
                % (name, st.get("compiles", 0),
                   st.get("distinct_avals", 0))
                for name, st in sorted(
                    hot.items(), key=lambda kv: -kv[1]["compiles"])]
    if keys:
        evidence.append("recent %s cache keys: %s"
                        % (worst, "; ".join(keys[-3:])))
    return [_finding(
        "recompile-storm", compile_share,
        "recompile storm: %d op(s), worst %r (%d compiles) — "
        "compile is %.0f%% of step time"
        % (len(hot), worst, hot[worst].get("compiles", 0),
           compile_share * 100),
        worst, evidence,
        "hoist the churning attr into traced_attrs or stabilize input "
        "shapes — every recompile stalls dispatch for a full XLA "
        "compile (docs/OBSERVABILITY.md 'Recompile-storm detector')")]


def _check_eager_dispatch(dump):
    """Eager per-op dispatch tax: warm dispatch (+ compile) dominating
    the step while the run never used the compiled whole-step path —
    the exact profile ``MXNET_TPU_COMPILED_STEP`` exists for
    (compiled_step.py: fwd+bwd+update traced into ONE donated XLA
    program, ~1 warm dispatch per step instead of one per op).
    Projected savings derive from the warm-dispatch counters: of the
    measured ``dispatch_warm`` share, a compiled step keeps roughly
    1/calls-per-step (one remaining dispatch) and fuses the rest."""
    snap = dump.get("snapshot", dump)
    counters = snap.get("counters") or {}
    if counters.get("compiled_step_steps"):
        return []  # the run already trains through the compiled path
    steps = counters.get("trainer_steps", 0)
    if not steps:
        return []
    a = _anatomy_of(dump)
    if not a.get("steps"):
        return []
    dw = (a["phases"].get("dispatch_warm") or {}).get("share") or 0.0
    comp = (a["phases"].get("compile") or {}).get("share") or 0.0
    share = dw + comp
    if share < SHARE_WARN:
        return []
    totals = snap.get("totals") or {}
    warm = totals.get("jit_cache_hits", 0)
    calls_per_step = warm / steps
    if calls_per_step < 2:
        return []  # already ~one dispatch per step: nothing to collapse
    projected = dw * (1.0 - 1.0 / calls_per_step)
    dw_ms = (a["phases"].get("dispatch_warm") or {}).get("mean_ms") or 0.0
    return [_finding(
        "eager-dispatch-tax", share,
        "eager dispatch is %.0f%% of step time (%.0f warm op "
        "dispatches/step) — whole-step compilation would collapse "
        "them to ~1, saving ~%.0f%% of step time"
        % (share * 100, calls_per_step, projected * 100),
        "dispatch_warm",
        ["%d warm jit-cache hits over %d step(s): %.1f dispatches/"
         "step at %.3f ms/step of warm-dispatch wall"
         % (warm, steps, calls_per_step, dw_ms),
         "compile share %.0f%% also amortizes to one program per "
         "input signature under the compiled step" % (comp * 100)],
        "train through the fused whole-step program: "
        "cs = trainer.compile(net, loss); cs.step(x, y) — or set "
        "MXNET_TPU_COMPILED_STEP=1 where the launch wiring honors it "
        "(docs/COMPILED_STEP.md); the eager path remains the "
        "debugging/interop mode")]


def _check_host_sync(dump):
    """Deliberate host-sync sinks (monitor stats, health drain) whose
    per-step cost stopped being small."""
    snap = dump.get("snapshot", dump)
    counters = snap.get("counters") or {}
    a = _anatomy_of(dump)
    wall_sum_ms = (a.get("step_wall_ms") or {}).get("sum_ms") \
        if a.get("steps") else None
    out = []
    for counter, anchor, what, action in (
            ("monitor_seconds", "monitor:stat",
             "Monitor stat host-syncs",
             "drop the Monitor (or raise its interval) for production "
             "runs; the default stat path is device-resident but "
             "toc() still syncs"),
            ("health_seconds", "health:drain",
             "numerics-health drains",
             "raise MXNET_TPU_HEALTH_INTERVAL or trim "
             "MXNET_TPU_HEALTH_STATS — the drain is the layer's one "
             "deliberate sync")):
        secs = counters.get(counter, 0.0)
        if not secs:
            continue
        if wall_sum_ms:
            share = (secs * 1e3) / wall_sum_ms
        else:
            continue  # no step clock: cannot rank, skip
        if share < SYNC_SHARE_NOTICE:
            continue
        out.append(_finding(
            "host-sync", share,
            "%s are %.0f%% of step time" % (what, share * 100),
            anchor,
            ["%s=%.3fs over %d step(s)"
             % (counter, secs, a["steps"])],
            action, warn_at=2 * SYNC_SHARE_NOTICE))
    return out


def _check_roofline(dump, top=3):
    """Top profiled ops sitting far above their cost-model roofline
    bound, weighted by their share of total profiled dispatch time."""
    snap = dump.get("snapshot", dump)
    rows = dump.get("roofline") or _rts.roofline(snap)
    totals = snap.get("totals") or {}
    total_secs = totals.get("dispatch_seconds") or 0.0
    if not total_secs:
        return []
    # scores are "share of step time": scale each op's share of the
    # profiled dispatch time by dispatch_warm's share of the step when
    # the anatomy is available (dispatch is only part of a step)
    a = _anatomy_of(dump)
    dispatch_share = (a.get("phases", {}).get("dispatch_warm")
                      or {}).get("share", 1.0) if a.get("steps") else 1.0
    culprits = []
    for r in rows:
        if "headroom_us" not in r or "us_per_call" not in r:
            continue
        if r["headroom_us"] < HEADROOM_RATIO * r["us_per_call"]:
            continue
        op = (snap.get("ops") or {}).get(r["op"]) or {}
        op_secs = op.get("dispatch_seconds", 0.0)
        share = op_secs / total_secs
        if share < SHARE_NOTICE / 2:
            continue
        culprits.append((share * dispatch_share, share, r))
    if not culprits:
        return []
    culprits.sort(key=lambda sr: -sr[0])
    culprits = culprits[:top]
    total_share = sum(s for s, _, _ in culprits)
    worst = culprits[0][2]
    evidence = []
    for _score, share, r in culprits:
        evidence.append(
            "%s: %.1f us/call vs %.1f us roofline bound (%.0f us "
            "headroom/call, %.0f%% of profiled dispatch time%s)"
            % (r["op"], r["us_per_call"], r.get("bound_us", 0.0),
               r["headroom_us"], share * 100,
               (", %.1f GB/s achieved" % r["achieved_gbps"])
               if r.get("achieved_gbps") else ""))
    return [_finding(
        "roofline-headroom", total_share,
        "%d op(s) far above their roofline bound, worst %r"
        % (len(culprits), worst["op"]),
        worst["op"], evidence,
        "these are cache-warm HOST dispatch rates — confirm with the "
        "measured device trace (tools/profile_step.py), then fuse/"
        "batch the op or fix its layout")]


def _check_stragglers(dump):
    """One PS shard's RTT p99 an outlier vs the other shards — the
    single-rank view of the cluster straggler check (per-shard
    ``kv:push_rtt:shardN`` / ``kv:pull_rtt:shardN`` histograms)."""
    snap = dump.get("snapshot", dump)
    hists = snap.get("histograms") or {}
    out = []
    for op in ("push", "pull"):
        prefix = "kv:%s_rtt:shard" % op
        group = [(name, h) for name, h in hists.items()
                 if name.startswith(prefix)
                 and h.get("p99") is not None]
        if len(group) < 2:
            continue
        worst_name, worst = max(group, key=lambda nh: nh[1]["p99"])
        med = _histogram.median_of_others(
            [(n, h["p99"]) for n, h in group], worst_name)
        if not med or med <= 0:
            continue
        ratio = worst["p99"] / med
        if ratio <= _histogram.STRAGGLER_RATIO:
            continue
        a = _anatomy_of(dump)
        kv_share = (a.get("phases", {}).get("kvstore") or {}).get(
            "share", 0.0) if a.get("steps") else 0.0
        out.append(_finding(
            "kvstore-straggler", max(kv_share, SHARE_NOTICE),
            "PS shard straggler: %s p99 %.1f ms is %.1fx the other "
            "shards' median"
            % (worst_name, worst["p99"] * 1e3, ratio),
            worst_name,
            ["%s p99 %.3f ms vs median-of-others %.3f ms over %d "
             "sample(s)" % (worst_name, worst["p99"] * 1e3, med * 1e3,
                            worst.get("count", 0))],
            "investigate that shard's host/network; kvstore waits "
            "serialize the step (docs/OBSERVABILITY.md 'Distributed "
            "telemetry'; cross-rank view: diagnose.py --cluster)"))
    return out


def _check_retries(dump):
    snap = dump.get("snapshot", dump)
    counters = snap.get("counters") or {}
    retries = counters.get("kvstore_retries", 0)
    if not retries:
        return []
    return [_finding(
        "kvstore-retries", SHARE_NOTICE / 2,
        "%d kvstore retry(ies) (%d reconnect(s)) during the run"
        % (retries, counters.get("kvstore_reconnects", 0)),
        "kvstore",
        ["each retry adds a full backoff to some step's push/pull"],
        "check PS server health/logs; transient faults are retried "
        "with backoff but still stall the step "
        "(docs/CHECKPOINTING.md 'Dist kvstore hardening')")]


def _check_self_healing(dump):
    """Self-healing signals: dead-shard heartbeat warnings (a PS shard
    silent past MXNET_TPU_KV_DEADLINE — worker dumps) and server-side
    duplicate suppression (retried mutations acked from the
    exactly-once seq table — server dumps), so recovery drills and
    real incidents both show up in the doctor report."""
    snap = dump.get("snapshot", dump)
    counters = snap.get("counters") or {}
    out = []
    dead = counters.get("kvstore_dead_shard_warnings", 0)
    if dead:
        out.append(_finding(
            "kvstore-dead-shard", SHARE_WARN,
            "%d dead-shard warning(s): a PS shard went unresponsive "
            "past MXNET_TPU_KV_DEADLINE" % dead,
            "kvstore",
            ["every deadline window a shard stays silent, pushes to it "
             "sit in the retry/backoff ladder"],
            "check that server process/host; run under tools/launch.py "
            "with MXNET_TPU_SUPERVISE=N so a dead server is relaunched "
            "and self-restores from its durable shard checkpoint "
            "(docs/CHECKPOINTING.md 'Server-side durability')"))
    dup = counters.get("kvstore_dup_suppressed", 0)
    if dup:
        restores = counters.get("kvstore_server_restores", 0)
        evidence = ["reply-loss retries were acked from the "
                    "(client_id, seq) table without re-applying — "
                    "exactly-once held"]
        if restores:
            evidence.append("%d store restore(s) from the durable "
                            "shard manifest this run" % restores)
        out.append(_finding(
            "kvstore-dedup", SHARE_NOTICE / 4,
            "%d retried mutation(s) suppressed as duplicate(s) "
            "server-side" % dup,
            "kvstore", evidence,
            "expected during reply_drop/restart_after drills; in "
            "production it means replies are being lost — check the "
            "network and server load (docs/CHECKPOINTING.md "
            "'Server-side durability')"))
    return out


def _check_zero_allgather(dump):
    """ZeRO weight-update sharding: the per-step parameter all-gather
    is pure overhead bought to shrink per-device state ~n×.  When it
    moves more than ``ZERO_AG_RATIO`` of the compiled step's total
    bytes-accessed, the trade has inverted — the collectives cost more
    traffic than the forward/backward math moves, the signature of a
    model too small (or a per-device batch too thin) for the dp width.
    """
    snap = dump.get("snapshot", dump)
    counters = snap.get("counters") or {}
    zsteps = counters.get("zero_steps", 0)
    ag = counters.get("zero_allgather_bytes", 0)
    if not zsteps or not ag:
        return []
    per_step = ag / zsteps
    bpc = ((snap.get("costs") or {}).get("compiled_step") or {}).get(
        "bytes_per_call")
    if not bpc:
        return []
    share = per_step / bpc
    if share < ZERO_AG_RATIO:
        return []
    rs = counters.get("zero_reduce_bytes", 0)
    return [_finding(
        "zero-allgather-dominated", min(share, 1.0),
        "ZeRO param all-gather moves %.0f%% of the compiled step's "
        "bytes-accessed (%.1f MB/step of %.1f MB/step)"
        % (share * 100, per_step / 1e6, bpc / 1e6),
        "zero",
        ["%.1f MB/step all-gather + %.1f MB/step reduce-scatter over "
         "%d zero step(s); compiled-step cost model reads %.1f "
         "MB/step total" % (per_step / 1e6,
                            rs / zsteps / 1e6, zsteps, bpc / 1e6)],
        "raise the per-device batch (amortizes the gather over more "
        "math), shrink the dp width, or drop zero=True — at this "
        "model size replicated state is cheaper than the collectives "
        "(docs/ZERO.md 'When not to shard')")]


# ---------------------------------------------------------- x-ray rules


def _xray_newest(dump, zero=None):
    """The newest x-ray table in ``dump`` (optionally restricted to
    zero / non-zero programs), or None."""
    snap = dump.get("snapshot", dump)
    programs = ((snap.get("xray") or {}).get("programs")) or []
    if zero is not None:
        programs = [t for t in programs if bool(t.get("zero")) == zero]
    return programs[-1] if programs else None


def _check_xray_scope(dump):
    """**xray-scope-dominated** — one block's scope (forward+backward
    summed) carries ``XRAY_DOMINANT_SHARE`` of the fused program's
    flops or bytes: the named block is where the step's cost lives."""
    t = _xray_newest(dump)
    if t is None:
        return []
    blocks = {}
    for scope, rec in (t.get("scopes") or {}).items():
        if scope.startswith("forward/"):
            path = scope[len("forward/"):]
        elif scope.startswith("backward/"):
            path = scope[len("backward/"):]
        else:
            continue  # optimizer / zero_* regions have their own rules
        agg = blocks.setdefault(path, {"flops": 0.0, "bytes": 0.0})
        agg["flops"] += rec.get("flops_share") or 0.0
        agg["bytes"] += rec.get("bytes_share") or 0.0
    if not blocks:
        return []
    path, agg = max(blocks.items(),
                    key=lambda kv: max(kv[1]["flops"], kv[1]["bytes"]))
    share = max(agg["flops"], agg["bytes"])
    if share < XRAY_DOMINANT_SHARE:
        return []
    return [_finding(
        "xray-scope-dominated", min(share, 1.0),
        "block '%s' carries %.0f%% of the fused program's %s"
        % (path, share * 100,
           "flops" if agg["flops"] >= agg["bytes"] else "bytes"),
        path,
        ["fwd+bwd share of program %s: flops %.0f%%, bytes %.0f%% "
         "(x-ray of %s, %d instruction(s))"
         % (t.get("label", "compiled_step"), agg["flops"] * 100,
            agg["bytes"] * 100, t.get("label", "compiled_step"),
            t.get("instructions", 0))],
        "this block is the fused step — aim kernel/layout/precision "
        "work here and cite the x-ray share in the perf PR "
        "(docs/OBSERVABILITY.md 'Fused-step X-ray')",
        warn_at=XRAY_DOMINANT_WARN)]


def _check_xray_zero_collective(dump):
    """**xray-zero-collective-share** — collective bytes vs compute
    bytes INSIDE the ZeRO program: the param all-gather / grad
    reduce-scatter traffic against the forward+backward scopes' bytes
    (the math that traffic feeds).  Prefers the HLO-measured collective
    instructions; on single-device traces (where GSPMD elides the
    collectives) it falls back to the measured per-step
    ``zero_allgather_bytes``/``zero_reduce_bytes`` counters."""
    t = _xray_newest(dump, zero=True)
    if t is None:
        return []
    scopes = t.get("scopes") or {}
    compute = sum((rec.get("bytes") or 0.0)
                  for scope, rec in scopes.items()
                  if scope.startswith(("forward/", "backward/")))
    if not compute:
        compute = (t.get("totals") or {}).get("bytes_accessed") or 0.0
    if not compute:
        return []
    coll = sum((rec.get("collective_bytes") or 0.0)
               for rec in scopes.values())
    coll += (t.get("unattributed") or {}).get("collective_bytes") or 0.0
    source = "HLO collective instructions"
    if not coll:
        snap = dump.get("snapshot", dump)
        counters = snap.get("counters") or {}
        zsteps = counters.get("zero_steps", 0)
        if zsteps:
            coll = (counters.get("zero_allgather_bytes", 0)
                    + counters.get("zero_reduce_bytes", 0)) / zsteps
            source = "zero_allgather/reduce counters (single-device " \
                     "trace: GSPMD elided the collectives)"
    if not coll:
        return []
    ratio = coll / compute
    if ratio < XRAY_ZERO_COLL_SHARE:
        return []
    # score = collectives' fraction of the combined collective+compute
    # traffic, so it stays a [0,1) share like every other rule
    return [_finding(
        "xray-zero-collective-share", coll / (coll + compute),
        "ZeRO collectives move %.0f%% of what the fwd+bwd math moves "
        "(%.1f vs %.1f MB/step)" % (ratio * 100, coll / 1e6,
                                    compute / 1e6),
        "zero",
        ["measured from %s; program %s, %d instruction(s); "
         "forward+backward scopes move %.1f MB"
         % (source, t.get("label", "zero_step"),
            t.get("instructions", 0), compute / 1e6)],
        "the sharding's data movement rivals the math it feeds: raise "
        "the per-device batch, shrink the dp width, or drop zero=True "
        "(docs/ZERO.md 'When not to shard')")]


def _check_xray_optimizer(dump):
    """**xray-optimizer-share** — the fused update region's bytes
    dominate the program: the step is optimizer-state-bound."""
    t = _xray_newest(dump)
    if t is None:
        return []
    rec = (t.get("scopes") or {}).get("optimizer")
    if not rec:
        return []
    share = rec.get("bytes_share") or 0.0
    if share < XRAY_OPT_SHARE:
        return []
    return [_finding(
        "xray-optimizer-share", min(share, 1.0),
        "the fused optimizer update moves %.0f%% of the program's "
        "bytes (%.1f of %.1f MB)"
        % (share * 100, rec.get("bytes", 0.0) / 1e6,
           ((t.get("totals") or {}).get("bytes_accessed") or 0.0)
           / 1e6),
        "optimizer",
        ["update-region flops share %.0f%%, bytes share %.0f%% "
         "(x-ray of %s)" % ((rec.get("flops_share") or 0.0) * 100,
                            share * 100,
                            t.get("label", "compiled_step"))],
        "the step is state-bound: check the optimizer state dtype "
        "(fp32 master copies double the traffic), shard the state "
        "with zero=True (docs/ZERO.md), or pick a lighter-state "
        "optimizer")]


# --------------------------------------------------------- serving rules


def _check_serving(dump):
    """Serving-layer findings from an ``InferenceServer`` run's dump:

    - **serve-queue-dominated** — the ``serve:queue_wait`` p99 exceeds
      ``SERVE_QUEUE_RATIO`` x the ``serve:batch`` compute p99: requests
      spend longer waiting for a batch slot than being computed, the
      signature of offered load past this replica's capacity.
    - **serve-bucket-churn** — more bucket-executable builds than the
      ladder has buckets past warmup: executables are being rebuilt
      (reconstructed servers, shape churn reaching the build path),
      each one a full XLA compile on the serving path.
    """
    snap = dump.get("snapshot", dump)
    serving = snap.get("serving") or {}
    counters = snap.get("counters") or {}
    hists = snap.get("histograms") or {}
    requests = serving.get("requests") or counters.get(
        "serve_requests", 0)
    if not requests:
        return []
    out = []
    qw = hists.get("serve:queue_wait") or {}
    batch = hists.get("serve:batch") or {}
    e2e = hists.get("serve:e2e") or {}
    if requests >= SERVE_MIN_REQUESTS and qw.get("p99") \
            and batch.get("p99"):
        ratio = qw["p99"] / batch["p99"]
        if ratio > SERVE_QUEUE_RATIO:
            # score = the fraction of a served request's life spent
            # queueing (the serving analog of "share of step time")
            share = (qw["mean"] / e2e["mean"]) \
                if e2e.get("mean") else min(1.0, ratio / 10.0)
            occ = serving.get("mean_occupancy")
            evidence = [
                "queue_wait p99 %.3f ms vs batch compute p99 %.3f ms "
                "(%.1fx) over %d request(s)"
                % (qw["p99"] * 1e3, batch["p99"] * 1e3, ratio,
                   requests)]
            if e2e.get("p99") is not None:
                evidence.append("end-to-end p99 %.3f ms"
                                % (e2e["p99"] * 1e3))
            if occ is not None:
                evidence.append("mean bucket occupancy %.0f%% (ladder "
                                "%s)" % (occ * 100,
                                         serving.get("buckets")))
            out.append(_finding(
                "serve-queue-dominated", share,
                "serving is queue-dominated: queue-wait p99 is %.1fx "
                "the batch-compute p99" % ratio,
                "serve:queue_wait", evidence,
                "this replica is past capacity — raise the max bucket "
                "(bigger batches amortize dispatch), add a replica "
                "behind the load balancer, or shed load earlier with a "
                "smaller MXNET_TPU_SERVE_QUEUE (docs/SERVING.md "
                "'Latency SLOs')"))
    # take the MAX of the newest server's section and the process-wide
    # counters: a process re-creating servers per batch (the exact
    # churn scenario) shows a small per-server section value while the
    # cumulative counter carries the real build count
    compiles = max(serving.get("bucket_compiles") or 0,
                   counters.get("serve_bucket_compiles", 0))
    ladder = serving.get("buckets") or []
    batches = max(serving.get("batches") or 0,
                  counters.get("serve_batches", 0))
    # guard only on having SERVED something (a warmup-only process
    # compiles <= len(ladder) and stays silent anyway); requiring
    # batches > compiles would mute exactly the worst churn —
    # server-per-batch recreation compiles the ladder per batch
    if ladder and compiles > len(ladder) and batches:
        extra = compiles - len(ladder)
        out.append(_finding(
            "serve-bucket-churn", SHARE_NOTICE * min(4.0, extra),
            "bucket-executable churn: %d build(s) for a %d-bucket "
            "ladder" % (compiles, len(ladder)),
            "serve_bucket_compiles",
            ["%d build(s) past the one-per-bucket warmup across %d "
             "batch(es) — every extra build is a full XLA compile on "
             "the serving path" % (extra, batches)],
            "executables should compile once per bucket and be cached "
            "for the server's life — avoid re-creating servers per "
            "request batch and keep request shapes on the configured "
            "ladder (docs/SERVING.md 'Bucket ladder')"))
    return out


def _check_slo(dump):
    """SLO / error-budget findings over the ``slo`` section (the
    multi-window burn-rate evaluation ``mxnet_tpu/slo.py`` bakes into
    every snapshot/diag dump):

    - **slo-fast-burn** — an objective's fast window pair (5m/1h,
      scaled) both burn at >= ``slo.FAST_BURN`` (14.4): at that rate a
      30-day error budget is gone in hours.  The page-now signal, and
      the trigger of the ``MXNET_TPU_AUTOPILOT_SLO`` reflex.
    - **slo-budget-exhausted** — the objective's whole error budget is
      already spent over the observed run: every further bad event is
      an SLO violation in the open.
    """
    snap = dump.get("snapshot", dump)
    slo = snap.get("slo") or {}
    out = []
    for ob in slo.get("objectives") or []:
        name = ob.get("name")
        budget = 1.0 - (ob.get("target") or 0.0)
        w = ob.get("windows") or {}
        b5 = (w.get("5m") or {}).get("burn", 0.0)
        b1h = (w.get("1h") or {}).get("burn", 0.0)
        rem = ob.get("budget_remaining")
        if ob.get("fast_burn"):
            # score 0.5 at the firing threshold, saturating at 2x it —
            # a fast burn is always at least a warn
            score = min(1.0, max(b5, b1h) / (2.0 * _slo.FAST_BURN))
            evidence = [
                "fast pair burning: 5m burn %.1f (%d event(s)), 1h "
                "burn %.1f (%d event(s)) — both >= %.1f"
                % (b5, (w.get("5m") or {}).get("events", 0), b1h,
                   (w.get("1h") or {}).get("events", 0),
                   _slo.FAST_BURN),
                "objective %s: target %.5g%%, budget %.5g%%, %d good /"
                " %d bad" % (name, (ob.get("target") or 0) * 100,
                             budget * 100, ob.get("good", 0),
                             ob.get("bad", 0))]
            if rem is not None:
                evidence.append("error budget remaining %.1f%%"
                                % (rem * 100))
            out.append(_finding(
                "slo-fast-burn", score,
                "SLO %r fast burn: spending error budget at %.1fx the "
                "sustainable rate" % (name, max(b5, b1h)),
                "slo:%s" % name, evidence,
                "act now — shed load (smaller MXNET_TPU_SERVE_QUEUE), "
                "add capacity, or roll back the last change; the "
                "MXNET_TPU_AUTOPILOT_SLO reflex can nudge the serving "
                "knobs (dry-run unless armed; docs/OBSERVABILITY.md "
                "'Request x-ray & SLOs')"))
        if rem is not None and rem <= 0.0 \
                and (ob.get("total") or 0) >= _slo.MIN_EVENTS:
            out.append(_finding(
                "slo-budget-exhausted", min(1.0, 0.5 - rem),
                "SLO %r error budget exhausted (%.1f%% remaining)"
                % (name, rem * 100),
                "slo:%s" % name,
                ["%d bad of %d event(s) vs a %.5g%% budget"
                 % (ob.get("bad", 0), ob.get("total", 0),
                    budget * 100)],
                "the objective is blown for this window — freeze risky "
                "rollouts, fix the dominant bad-outcome class (see the "
                "per-outcome breakdown in the serving section / "
                "diagnose.py --requests), and let the budget recover"))
    return out


# ----------------------------------------------------------- trend rules


def _lin_slope(xs, ys):
    """Least-squares slope of ys over xs (0 for a degenerate x span)."""
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    den = sum((x - mx) ** 2 for x in xs)
    if not den:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / den


def _window_means(vals):
    """``(early mean, late mean, window size)`` over the first/last
    quarter of the series (min 3 samples per window)."""
    k = max(3, len(vals) // 4)
    early = vals[:k]
    late = vals[-k:]
    return sum(early) / len(early), sum(late) / len(late), k


def _phase_means(samples):
    """Per-phase mean ms over samples that carry a stepstats window."""
    sums: dict = {}
    counts: dict = {}
    for s in samples:
        for p, v in (s.get("phases_ms") or {}).items():
            sums[p] = sums.get(p, 0.0) + v
            counts[p] = counts.get(p, 0) + 1
    return {p: sums[p] / counts[p] for p in sums}


def _grown_phase(early_samples, late_samples):
    """``(phase, early ms, late ms)`` of the phase whose mean grew the
    most between the windows, or None without phase data."""
    early = _phase_means(early_samples)
    late = _phase_means(late_samples)
    best = None
    for p, lv in late.items():
        ev = early.get(p, 0.0)
        if best is None or lv - ev > best[2] - best[1]:
            best = (p, ev, lv)
    if best is None or best[2] <= best[1]:
        return None
    return best


def _check_leak(samples):
    """Monotonic live-device-bytes growth: the leak signature no single
    snapshot can see.  Needs the device-memory tracker feeding the
    samples (``MXNET_TPU_DIAG`` / ``MXNET_TPU_MEMORY_TRACK=1``)."""
    pts = [(s.get("step", i), s["live_bytes"])
           for i, s in enumerate(samples)
           if s.get("live_bytes") is not None]
    if len(pts) < TREND_MIN_SAMPLES:
        return []
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    growth = ys[-1] - ys[0]
    slope = _lin_slope(xs, ys)
    nondec = sum(1 for a, b in zip(ys, ys[1:]) if b >= a) \
        / max(1, len(ys) - 1)
    if slope < LEAK_SLOPE_BYTES or growth < LEAK_MIN_GROWTH \
            or nondec < LEAK_MONOTONIC_FRAC:
        return []
    steps = max(1, xs[-1] - xs[0])
    return [_finding(
        "timeline-leak", 2 * SHARE_WARN,
        "device-memory leak: live bytes grew %.1f MB over %d step(s) "
        "(%.1f KB/step slope)"
        % (growth / 1e6, steps, slope / 1e3),
        "live_bytes",
        ["live bytes %.2f MB at step %s -> %.2f MB at step %s"
         % (ys[0] / 1e6, xs[0], ys[-1] / 1e6, xs[-1]),
         "regression slope %.0f bytes/step; %.0f%% of deltas "
         "non-decreasing" % (slope, nondec * 100)],
        "find the retaining op in the dump's device-memory per-op "
        "table (python -m mxnet_tpu.runtime_stats <dump>); usual "
        "suspects: a growing Python list of NDArrays, autograd graphs "
        "kept past backward, metric state never reset "
        "(docs/OBSERVABILITY.md 'Live metrics & trends')")]


def _check_throughput(samples):
    """Sustained slowdown: recent-window mean step wall vs the early
    window's, with the fastest-growing phase named when the samples
    carry a stepstats breakdown."""
    timed = [s for s in samples if s.get("wall_ms") is not None]
    if len(timed) < TREND_MIN_SAMPLES:
        return []
    walls = [s["wall_ms"] for s in timed]
    early, late, k = _window_means(walls)
    if early <= 0:
        return []
    ratio = late / early
    if ratio < 1.0 + TREND_SLOWDOWN:
        return []
    slow_frac = 1.0 - early / late
    evidence = ["step wall mean %.3f ms (first %d sample(s)) -> "
                "%.3f ms (last %d): %.2fx" % (early, k, late, k, ratio)]
    thr = [s.get("throughput") for s in timed if s.get("throughput")]
    if len(thr) >= 2 * k:
        te = sum(thr[:k]) / k
        tl = sum(thr[-k:]) / k
        evidence.append("throughput %.1f -> %.1f samples/s" % (te, tl))
    grown = _grown_phase(timed[:k], timed[-k:])
    action = ("profile an early and a late window (MXNET_TPU_PROFILE) "
              "and diff their dumps (diagnose.py --compare); no phase "
              "attribution in these samples — enable "
              "MXNET_TPU_STEPSTATS to name the growing phase")
    anchor = "step_wall"
    if grown is not None:
        p, ev, lv = grown
        evidence.append("fastest-growing phase: %s %.3f -> %.3f "
                        "ms/step" % (p, ev, lv))
        anchor = "phase:%s" % p
        action = ("the growth sits in phase %r — check that "
                  "subsystem's inputs over time (io queue depth, kv "
                  "RTT drift, compile churn); confirm with "
                  "diagnose.py --compare on an early vs late diag dump"
                  % p)
    return [_finding(
        "timeline-throughput", slow_frac,
        "throughput regression: recent steps %.2fx slower than the "
        "early window" % ratio,
        anchor, evidence, action, warn_at=1.0 - 1.0 /
        (1.0 + TREND_SLOWDOWN))]


def _check_spikes(samples):
    """Step-time spikes vs the series median, with periodicity
    detection and the offending phase named.  The first
    ``SPIKE_WARMUP`` samples are exempt (late compiles / allocator
    warmup read as spikes otherwise)."""
    body = [s for s in samples[SPIKE_WARMUP:]
            if s.get("wall_ms") is not None]
    if len(body) < TREND_MIN_SAMPLES:
        return []
    ordered = sorted(s["wall_ms"] for s in body)
    med = ordered[len(ordered) // 2]
    if med <= 0:
        return []
    spikes = [s for s in body if s["wall_ms"] > SPIKE_RATIO * med]
    if len(spikes) < SPIKE_MIN_COUNT:
        return []
    total = sum(s["wall_ms"] for s in body)
    excess = sum(s["wall_ms"] - med for s in spikes)
    share = excess / total if total else 0.0
    if share < SPIKE_MIN_SHARE:
        return []
    steps = [s.get("step", 0) for s in spikes]
    diffs = [b - a for a, b in zip(steps, steps[1:])]
    period = None
    if diffs and diffs[0] > 1 and \
            all(abs(d - diffs[0]) <= 1 for d in diffs):
        period = diffs[0]
    worst = max(spikes, key=lambda s: s["wall_ms"])
    evidence = ["%d spike(s) > %.0fx the median step wall (%.3f ms); "
                "worst step %s at %.3f ms"
                % (len(spikes), SPIKE_RATIO, med,
                   worst.get("step", "?"), worst["wall_ms"])]
    if period:
        evidence.append("periodic: one spike every ~%d step(s) — a "
                        "cadence, not noise" % period)
    # name the phase carrying the spike: worst spike's phases vs the
    # non-spike phase means
    quiet = [s for s in body if s not in spikes]
    grown = _grown_phase(quiet, [worst])
    anchor = "step_wall"
    action = ("align the spike steps with your loop's cadences "
              "(checkpoint/eval/logging every N steps); no phase "
              "attribution in these samples — enable "
              "MXNET_TPU_STEPSTATS to name the phase")
    if grown is not None:
        p, ev, lv = grown
        evidence.append("offending phase: %s %.3f ms (quiet steps) -> "
                        "%.3f ms in the worst spike" % (p, ev, lv))
        anchor = "phase:%s" % p
        action = ("the spikes sit in phase %r — check that "
                  "subsystem's every-N-steps work (checkpoint "
                  "interval, eval loop, log flush); spread or async "
                  "it" % p)
    return [_finding(
        "timeline-spikes", share,
        "step-time spikes: %d step(s) > %.0fx the median%s"
        % (len(spikes), SPIKE_RATIO,
           (", every ~%d steps" % period) if period else ""),
        anchor, evidence, action)]


def _check_kv_drift(samples, top=3):
    """A kv push/pull-RTT series whose windowed p99 drifts up over the
    run — the emerging straggler, per shard."""
    series: dict = {}
    for s in samples:
        for name, h in (s.get("kv_rtt_ms") or {}).items():
            if h.get("p99_ms") is not None:
                series.setdefault(name, []).append(h["p99_ms"])
    out = []
    for name, vals in sorted(series.items()):
        if len(vals) < TREND_MIN_SAMPLES:
            continue
        early, late, k = _window_means(vals)
        if early <= 0:
            continue
        ratio = late / early
        if ratio <= KV_DRIFT_RATIO:
            continue
        out.append(_finding(
            "timeline-kv-drift", min(1.0, SHARE_NOTICE * ratio),
            "kv RTT drift: %s windowed p99 %.2fx its early window"
            % (name, ratio),
            name,
            ["windowed p99 mean %.3f ms (first %d sample(s)) -> "
             "%.3f ms (last %d)" % (early, k, late, k)],
            "that shard/route is degrading mid-run (host load, "
            "network, GC) — watch it live via the /metrics endpoint, "
            "cross-check ranks with diagnose.py --cluster, and see "
            "the MXNET_TPU_STRAGGLER_* warnings "
            "(docs/OBSERVABILITY.md 'Distributed telemetry')"))
    out.sort(key=lambda f: -f["score"])
    return out[:top]


def _check_timeline(samples):
    """Every trend rule over one timeline (a list of per-step sample
    dicts, oldest first)."""
    samples = [s for s in samples if isinstance(s, dict)]
    if len(samples) < TREND_MIN_SAMPLES:
        return []
    out = []
    out += _check_leak(samples)
    out += _check_throughput(samples)
    out += _check_spikes(samples)
    out += _check_kv_drift(samples)
    return out


# ----------------------------------------------------------- trace rules


def _union_us(intervals):
    """Total length of the union of (start, end) microsecond spans."""
    total = 0.0
    end = -1.0
    for s, e in sorted(intervals):
        if s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def _check_idle_gaps(trace):
    """Wall time inside ``trainer:step`` spans covered by NO other
    recorded span: untracked host work, or a host-sync wait the
    framework spans cannot see."""
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X" and "ts" in e]
    steps = [e for e in events if e.get("name") == "trainer:step"]
    if not steps:
        return []
    # coverage is per process track: in a merged multi-rank trace,
    # another rank's spans must not mask this rank's gap
    others_by_pid: dict = {}
    for e in events:
        if e.get("name") != "trainer:step":
            others_by_pid.setdefault(e.get("pid", 0), []).append(
                (e["ts"], e["ts"] + e.get("dur", 0.0)))
    total_gap = 0.0
    total_dur = 0.0
    worst = (0.0, None)
    for st in steps:
        s0, s1 = st["ts"], st["ts"] + st.get("dur", 0.0)
        others = others_by_pid.get(st.get("pid", 0), ())
        covered = _union_us([(max(a, s0), min(b, s1))
                             for a, b in others if b > s0 and a < s1])
        gap = max(0.0, (s1 - s0) - covered)
        total_gap += gap
        total_dur += s1 - s0
        if gap > worst[0]:
            worst = (gap, st)
    if not total_dur:
        return []
    share = total_gap / total_dur
    if share < IDLE_GAP_SHARE:
        return []
    wev = worst[1]
    return [_finding(
        "idle-gaps", share,
        "%.0f%% of trainer:step time is covered by no span"
        % (share * 100),
        "trainer:step",
        ["total gap %.3f ms across %d step span(s); worst step at "
         "ts=%.0f us with %.3f ms untracked"
         % (total_gap / 1e3, len(steps), wev["ts"], worst[0] / 1e3)],
        "host syncs or untracked user code inside step(): profile the "
        "gap region (chrome://tracing), audit with tools/mxlint "
        "host-sync-reachability, or wrap user phases in "
        "profiler.scope()")]


# --------------------------------------------------------------- driver


def live_dump(serving=True):
    """A LIGHT synthetic dump over the live process — just the
    sections the cheap rules (:func:`_check_recompiles`,
    :func:`_check_serving`) read: storm/counter dict reads plus the
    histogram and serving snapshots.  Deliberately NOT
    ``runtime_stats.snapshot()``: no cost aggregation, no xray, no
    memory walk — this runs inside the autopilot's evaluation tick and
    the ``/metrics`` scrape.  ``serving=False`` skips the serving
    snapshot too (the training-side tick doesn't read it)."""
    import sys as _sys

    storms = {}
    storm_keys = {}
    for name, st in list(_rts._STORM.items()):
        storms[name] = {"compiles": st.get("compiles", 0),
                        "warned": st.get("warned", 0),
                        "distinct_avals": len(st.get("avals") or ())}
        storm_keys[name] = [repr(k) for k in list(st.get("keys") or ())]
    snap = {"storms": storms, "counters": dict(_rts._COUNTERS),
            "histograms": _histogram.snapshot()}
    if serving:
        _serving = _sys.modules.get("mxnet_tpu.serving")
        snap["serving"] = _serving.snapshot() if _serving is not None \
            else {"enabled": False}
        # the SLO burn verdicts ride the serving-side dump: one guard
        # read when the layer is off, a bounded ring walk when on
        snap["slo"] = _slo.snapshot()
    else:
        snap["serving"] = {"enabled": False}
        snap["slo"] = {"enabled": False}
    return {"snapshot": snap, "recent_storm_keys": storm_keys}


def live_findings(top=20):
    """Doctor findings over the LIVE process: the trend rules over
    ``metrics_timeline``'s ring plus the recompile-storm and serving
    rules over :func:`live_dump`, ranked worst-first.  This is the
    shared signal the ``mxnet_tpu_doctor_finding`` Prometheus gauges
    export and the autopilot's reflexes act on — snapshot reads only,
    and it never raises (a scrape must not take down the endpoint)."""
    findings = []
    try:
        from . import metrics_timeline as _metrics

        samples = [s for s in _metrics.samples() if isinstance(s, dict)]
        if samples:
            findings += _check_timeline(samples)
        dump = live_dump()
        findings += _check_recompiles(dump)
        findings += _check_serving(dump)
        findings += _check_slo(dump)
    except Exception:  # diagnosis must never break the surface it rides
        pass
    findings.sort(key=lambda f: -f["score"])
    return findings[:top]


def diagnose(trace=None, dump=None, timeline=None, top=20):
    """Run every applicable rule over a loaded chrome ``trace``, diag
    ``dump``, and/or per-step ``timeline`` and return findings ranked
    worst-first (by estimated share of step time).  Any input may be
    None; rules missing their data contribute nothing.

    ``timeline`` is a list of ``metrics_timeline`` samples (or a
    ``{"samples": [...]}`` wrapper).  When omitted and the dump embeds
    a ``timeline`` section (``runtime_stats.diag_snapshot`` attaches
    the live ring), the trend rules run over that."""
    findings = []
    if dump is not None:
        findings += _check_step_anatomy(dump)
        findings += _check_recompiles(dump)
        findings += _check_eager_dispatch(dump)
        findings += _check_host_sync(dump)
        findings += _check_roofline(dump)
        findings += _check_stragglers(dump)
        findings += _check_retries(dump)
        findings += _check_self_healing(dump)
        findings += _check_zero_allgather(dump)
        findings += _check_xray_scope(dump)
        findings += _check_xray_zero_collective(dump)
        findings += _check_xray_optimizer(dump)
        findings += _check_serving(dump)
        findings += _check_slo(dump)
        if timeline is None:
            timeline = dump.get("timeline")
    if isinstance(timeline, dict):
        timeline = timeline.get("samples")
    if timeline:
        findings += _check_timeline(list(timeline))
    if trace is not None:
        findings += _check_idle_gaps(trace)
    findings.sort(key=lambda f: -f["score"])
    return findings[:top]


def render(findings, inputs=()):
    """Human report: ranked findings with evidence and next actions."""
    lines = ["Perf doctor: %d finding(s)%s"
             % (len(findings),
                (" over %s" % ", ".join(inputs)) if inputs else "")]
    if not findings:
        lines.append("no bottleneck past the reporting thresholds — "
                     "nothing obviously wrong in the provided "
                     "trace/dump")
    for i, f in enumerate(findings, 1):
        lines.append("%d. [%s] (%3.0f%% of step time) %s"
                     % (i, f["severity"].upper(), f["score"] * 100,
                        f["title"]))
        for ev in f["evidence"]:
            lines.append("     evidence: %s" % ev)
        lines.append("     next: %s" % f["action"])
    return "\n".join(lines)


def gh_annotation(level, message):
    """One GitHub workflow-command annotation line (the
    ``tools/mxlint --format github`` escaping convention)."""
    msg = message.replace("%", "%25").replace("\r", "%0D") \
        .replace("\n", "%0A")
    return "::%s::%s" % (level, msg)


def render_github(findings):
    """``::error``/``::notice`` annotation lines: warn-severity
    findings error, the rest notice."""
    lines = []
    for f in findings:
        level = "error" if f["severity"] == "warn" else "notice"
        lines.append(gh_annotation(
            level, "perf-doctor[%s] %s — next: %s"
            % (f["rule"], f["title"], f["action"])))
    return "\n".join(lines)
