// RecordIO reader/writer (reference: dmlc-core recordio as used by
// src/io/iter_image_recordio_2.cc; format shared with
// python/mxnet/recordio.py): little-endian uint32 magic 0xced7230a,
// uint32 length, payload, pad to 4-byte boundary.
//
// The reader does chunked sequential IO (one syscall per chunk, records
// parsed out of the buffer) and supports part-of-N sharding by byte range
// (reference: InputSplit semantics used for distributed data loading).
#ifndef MXTPU_RECORDIO_H_
#define MXTPU_RECORDIO_H_

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace mxtpu {

constexpr uint32_t kRecMagic = 0xced7230a;

class RecordReader {
 public:
  // part k of n: reader starts at the first record boundary at/after
  // offset k*size/n and stops at the first boundary at/after (k+1)*size/n.
  RecordReader(const std::string& path, size_t chunk_bytes, int part_index,
               int num_parts);
  ~RecordReader();

  // Returns false at end of shard.  The returned pointer is valid until the
  // next NextRecord/Reset call.
  bool NextRecord(const uint8_t** data, uint32_t* size);
  void Reset();
  // File offset of the next record NextRecord will return (pairs with
  // RecordWriter::Write's returned offset, for .idx-based random access).
  uint64_t Tell() const { return file_pos_ - (buf_len_ - buf_off_); }
  // Reposition to an absolute record offset (from Tell or a .idx file).
  // Unsharded readers only: .idx offsets are whole-file, shard windows
  // are a sequential-read pattern — mixing them would cross shards.
  void Seek(uint64_t pos);

 private:
  void FillBuffer();
  // Scan forward in the file from `pos` to the next magic-aligned record
  // boundary; returns the boundary offset.
  size_t SeekBoundary(size_t pos);

  FILE* f_{nullptr};
  std::string path_;
  size_t chunk_{0};
  bool sharded_{false};
  int num_parts_{1};
  size_t begin_{0}, end_{0};  // shard byte range (record-aligned)
  size_t file_pos_{0};        // next unread file offset
  std::vector<uint8_t> buf_;
  size_t buf_off_{0};   // parse cursor within buf_
  size_t buf_len_{0};   // valid bytes in buf_
  std::vector<uint8_t> rec_;  // scratch for records spanning chunks
};

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path);
  ~RecordWriter();
  // Returns byte offset of the record start (for .idx files).
  uint64_t Write(const uint8_t* data, uint32_t size);
  void Flush();
  uint64_t Tell() const { return pos_; }

 private:
  FILE* f_{nullptr};
  uint64_t pos_{0};
};

}  // namespace mxtpu

#endif  // MXTPU_RECORDIO_H_
