#include "pipeline.h"

#include <csetjmp>
#include <functional>

#ifdef MXTPU_USE_LIBJPEG
#include <cstdio>
#include <jpeglib.h>

namespace {
struct JpegErr {
  jmp_buf jb;
};
void JpegErrExit(j_common_ptr cinfo) {
  longjmp(static_cast<JpegErr*>(cinfo->client_data)->jb, 1);
}
}  // namespace
#endif

#include <cstring>
#include <stdexcept>

namespace mxtpu {

Pipeline::Pipeline(const PipelineConfig& cfg) : cfg_(cfg) {
  if (cfg_.sample_bytes == 0)
    throw std::runtime_error("pipeline: sample_bytes must be set");
  if (cfg_.queue_depth <= 0) cfg_.queue_depth = 2 * cfg_.num_workers;
  if (cfg_.queue_depth < 2) cfg_.queue_depth = 2;
  data_bytes_ = cfg_.sample_bytes * cfg_.batch_size;
  label_bytes_ = sizeof(float) * cfg_.label_width * cfg_.batch_size;
  reader_.reset(new RecordReader(cfg_.path, cfg_.chunk_bytes, cfg_.part_index,
                                 cfg_.num_parts));
  StartThreads();
}

Pipeline::~Pipeline() {
  StopThreads();
  // Free buffers still sitting in the reorder queue.
  for (auto& kv : done_) Release(kv.second);
}

void Pipeline::StartThreads() {
  stop_.store(false);
  io_done_ = false;
  io_seq_ = 0;
  next_out_ = 0;
  outstanding_ = 0;
  io_thread_ = std::thread([this] { IoLoop(); });
  for (int i = 0; i < cfg_.num_workers; ++i)
    workers_.emplace_back([this, i] { DecodeLoop(i); });
}

void Pipeline::StopThreads() {
  stop_.store(true);
  {
    std::lock_guard<std::mutex> lk(mu_);
    work_cv_.notify_all();
    done_cv_.notify_all();
    space_cv_.notify_all();
  }
  if (io_thread_.joinable()) io_thread_.join();
  for (auto& t : workers_)
    if (t.joinable()) t.join();
  workers_.clear();
}

void Pipeline::Reset() {
  StopThreads();
  for (auto& kv : done_) Release(kv.second);
  done_.clear();
  while (!work_q_.empty()) work_q_.pop();
  error_.clear();
  epoch_++;
  reader_->Reset();
  StartThreads();
}

void Pipeline::IoLoop() {
  // Shuffle buffer of records (reference: chunk-level + instance-level
  // shuffling in ImageRecordIOParser2; here a reservoir-style buffer).
  // Epoch counter mixed into the seed so each Reset() shuffles differently.
  std::mt19937_64 rng((cfg_.seed ? cfg_.seed : 0x5DEECE66DULL) +
                      0x9E3779B97F4A7C15ULL * epoch_);
  std::vector<std::vector<uint8_t>> shuf;
  shuf.reserve(cfg_.shuffle);
  std::vector<std::vector<uint8_t>> cur;
  cur.reserve(cfg_.batch_size);

  auto emit_record = [&](std::vector<uint8_t>&& rec) {
    cur.emplace_back(std::move(rec));
    if (static_cast<int>(cur.size()) == cfg_.batch_size) {
      std::unique_lock<std::mutex> lk(mu_);
      space_cv_.wait(lk, [&] {
        return stop_.load() || outstanding_ < cfg_.queue_depth;
      });
      if (stop_.load()) return false;
      Work w;
      w.recs = std::move(cur);
      w.seq = io_seq_++;
      outstanding_++;
      work_q_.push(std::move(w));
      work_cv_.notify_one();
      cur.clear();
      cur.reserve(cfg_.batch_size);
    }
    return true;
  };

  // First records of the epoch, kept to pad the final partial batch with
  // REAL samples (reference BatchLoader round_batch semantics — training
  // on fabricated zero samples would bias fit()).
  std::vector<std::vector<uint8_t>> head;

  const uint8_t* data;
  uint32_t size;
  bool ok = true;
  while (ok && !stop_.load() && reader_->NextRecord(&data, &size)) {
    std::vector<uint8_t> rec(data, data + size);
    if (static_cast<int>(head.size()) < cfg_.batch_size)
      head.push_back(rec);
    if (cfg_.shuffle > 0) {
      if (static_cast<int>(shuf.size()) < cfg_.shuffle) {
        shuf.emplace_back(std::move(rec));
      } else {
        size_t j = rng() % shuf.size();
        ok = emit_record(std::move(shuf[j]));
        shuf[j] = std::move(rec);
      }
    } else {
      ok = emit_record(std::move(rec));
    }
  }
  // Drain shuffle buffer in random order.
  while (ok && !stop_.load() && !shuf.empty()) {
    size_t j = rng() % shuf.size();
    std::swap(shuf[j], shuf.back());
    ok = emit_record(std::move(shuf.back()));
    shuf.pop_back();
  }
  // Partial final batch: count real samples, pad with wrapped records.
  if (ok && !stop_.load() && !cur.empty() && cfg_.last_batch_keep) {
    int real = static_cast<int>(cur.size());
    for (size_t i = 0; static_cast<int>(cur.size()) < cfg_.batch_size &&
                       !head.empty(); ++i)
      cur.push_back(head[i % head.size()]);
    std::unique_lock<std::mutex> lk(mu_);
    space_cv_.wait(lk, [&] {
      return stop_.load() || outstanding_ < cfg_.queue_depth;
    });
    if (!stop_.load()) {
      Work w;
      w.recs = std::move(cur);
      w.real_count = real;
      w.seq = io_seq_++;
      outstanding_++;
      work_q_.push(std::move(w));
      work_cv_.notify_one();
    }
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    io_done_ = true;
    work_cv_.notify_all();
    done_cv_.notify_all();
  }
}

int Pipeline::ParseHeader(const uint8_t* rec, uint32_t len, float* label,
                          const uint8_t** payload, size_t* payload_len) {
  // IRHeader (format of python recordio.pack: flag u32, label f32,
  // id u64, id2 u64, [flag>0: flag float32 labels], payload).
  if (len < 24) return -1;
  uint32_t flag;
  float slabel;
  std::memcpy(&flag, rec, 4);
  std::memcpy(&slabel, rec + 4, 4);
  const uint8_t* p = rec + 24;
  size_t remain = len - 24;
  for (int i = 0; i < cfg_.label_width; ++i) label[i] = 0.f;
  if (flag == 0) {
    label[0] = slabel;
  } else {
    // 64-bit guard: flag is untrusted record data; flag*4 in 32 bits can
    // wrap and defeat the bounds check
    uint64_t need = static_cast<uint64_t>(flag) * 4;
    if (remain < need) return -2;
    int n = static_cast<int>(flag) < cfg_.label_width
                ? static_cast<int>(flag)
                : cfg_.label_width;
    std::memcpy(label, p, static_cast<size_t>(n) * 4);
    p += need;
    remain -= need;
  }
  *payload = p;
  *payload_len = remain;
  return 0;
}

int Pipeline::DecodeRaw(const uint8_t* rec, uint32_t len, uint8_t* data,
                        float* label) {
  // Built-in decoder for raw samples: payload must be exactly
  // sample_bytes (raw tensor bytes).
  const uint8_t* p = nullptr;
  size_t remain = 0;
  int rc = ParseHeader(rec, len, label, &p, &remain);
  if (rc != 0) return rc;
  if (remain != cfg_.sample_bytes) return -3;
  std::memcpy(data, p, cfg_.sample_bytes);
  return 0;
}

#ifdef MXTPU_USE_LIBJPEG
int Pipeline::DecodeJpeg(const uint8_t* rec, uint32_t len, uint8_t* data,
                         float* label, std::mt19937* rng) {
  // Built-in JPEG decode + augment (reference:
  // src/io/iter_image_recordio_2.cc OpenCV decode +
  // image_aug_default.cc, done here with libjpeg).  Output: float32 CHW
  // minus per-channel mean, crop-or-center-fit to (img_h, img_w),
  // optional horizontal mirror — the exact python _augment semantics so
  // both paths produce identical batches.
  const uint8_t* p = nullptr;
  size_t remain = 0;
  int rc = ParseHeader(rec, len, label, &p, &remain);
  if (rc != 0) return rc;
  if (remain < 4 || p[0] != 0xFF || p[1] != 0xD8) return -10;  // not JPEG

  // declared BEFORE setjmp: a longjmp must not skip construction of a
  // non-trivial object (UB + leak per corrupt record otherwise)
  std::vector<uint8_t> img;
  jpeg_decompress_struct cinfo;
  jpeg_error_mgr jerr;
  JpegErr err_state;
  cinfo.err = jpeg_std_error(&jerr);
  jerr.error_exit = JpegErrExit;
  cinfo.client_data = &err_state;
  jpeg_create_decompress(&cinfo);
  if (setjmp(err_state.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -11;  // corrupt stream
  }
  jpeg_mem_src(&cinfo, p, remain);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -12;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int W = static_cast<int>(cinfo.output_width);
  const int H = static_cast<int>(cinfo.output_height);
  const int C = 3;
  img.resize(static_cast<size_t>(W) * H * C);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = img.data() + static_cast<size_t>(cinfo.output_scanline) * W * C;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  const int th = cfg_.img_h, tw = cfg_.img_w;
  if (cfg_.img_c != 3) return -13;
  if (cfg_.sample_bytes != static_cast<size_t>(C) * th * tw * 4) return -14;

  // source/dest offsets (python _augment: random crop when both dims
  // large enough, else centered crop-or-pad)
  int sy, sx, dy = 0, dx = 0;
  if (cfg_.rand_crop && H >= th && W >= tw) {
    sy = H > th ? static_cast<int>((*rng)() % (H - th + 1)) : 0;
    sx = W > tw ? static_cast<int>((*rng)() % (W - tw + 1)) : 0;
  } else {
    sy = H > th ? (H - th) / 2 : 0;
    sx = W > tw ? (W - tw) / 2 : 0;
    dy = th > H ? (th - H) / 2 : 0;
    dx = tw > W ? (tw - W) / 2 : 0;
  }
  const int ch = H < th ? H : th;
  const int cw = W < tw ? W : tw;
  const bool mirror = cfg_.rand_mirror && ((*rng)() & 1u);

  float* out = reinterpret_cast<float*>(data);
  for (int c = 0; c < C; ++c) {
    const float m = cfg_.mean[c];
    float* plane = out + static_cast<size_t>(c) * th * tw;
    // python _augment order: center-fit pads ZEROS, mirrors the whole
    // fitted canvas, THEN subtracts mean — so pad pixels are -mean and
    // the mirrored crop lands at column tw - dx - cw
    for (size_t i = 0; i < static_cast<size_t>(th) * tw; ++i) plane[i] = -m;
    const int dst_x0 = mirror ? (tw - dx - cw) : dx;
    for (int y = 0; y < ch; ++y) {
      const uint8_t* src = img.data() +
          (static_cast<size_t>(sy + y) * W + sx) * C + c;
      float* dst = plane + static_cast<size_t>(dy + y) * tw + dst_x0;
      if (mirror) {
        for (int x = 0; x < cw; ++x)
          dst[cw - 1 - x] = static_cast<float>(src[static_cast<size_t>(x) * C]) - m;
      } else {
        for (int x = 0; x < cw; ++x)
          dst[x] = static_cast<float>(src[static_cast<size_t>(x) * C]) - m;
      }
    }
  }
  return 0;
}
#else
int Pipeline::DecodeJpeg(const uint8_t*, uint32_t, uint8_t*, float*,
                         std::mt19937*) {
  return -20;  // built without libjpeg
}
#endif

void Pipeline::DecodeLoop(int worker_idx) {
  // per-worker rng: cfg seed + worker index + epoch — crops/mirrors
  // differ across workers AND epochs (like the shuffle rng in IoLoop)
  // yet reproduce exactly for a fixed seed
  std::mt19937 rng(static_cast<uint32_t>(
      cfg_.seed * 2654435761u + 0x9E3779B9u * (worker_idx + 1) +
      0x85EBCA6Bu * epoch_));
  for (;;) {
    Work w;
    {
      std::unique_lock<std::mutex> lk(mu_);
      work_cv_.wait(lk, [&] {
        return stop_.load() || !work_q_.empty() || io_done_;
      });
      if (stop_.load()) return;
      if (work_q_.empty()) {
        if (io_done_) return;
        continue;
      }
      w = std::move(work_q_.front());
      work_q_.pop();
    }
    Batch b;
    b.data = static_cast<uint8_t*>(pool_.Alloc(data_bytes_));
    b.label = static_cast<float*>(pool_.Alloc(label_bytes_));
    b.count = w.real_count >= 0 ? w.real_count
                                : static_cast<int>(w.recs.size());
    b.seq = w.seq;
    std::string err;
    for (size_t i = 0; i < w.recs.size(); ++i) {
      uint8_t* d = b.data + i * cfg_.sample_bytes;
      float* l = b.label + i * cfg_.label_width;
      int rc;
      if (cfg_.decode) {
        rc = cfg_.decode(cfg_.decode_ctx, w.recs[i].data(),
                         static_cast<uint32_t>(w.recs[i].size()), d, l);
      } else if (cfg_.builtin_jpeg) {
        rc = DecodeJpeg(w.recs[i].data(),
                        static_cast<uint32_t>(w.recs[i].size()), d, l, &rng);
        if (rc == -10 && cfg_.jpeg_fallback) {
          // non-JPEG payload (e.g. a PNG in a mixed .rec): route this
          // record through the Python callback instead of failing
          rc = cfg_.jpeg_fallback(cfg_.decode_ctx, w.recs[i].data(),
                                  static_cast<uint32_t>(w.recs[i].size()),
                                  d, l);
        }
      } else {
        rc = DecodeRaw(w.recs[i].data(),
                       static_cast<uint32_t>(w.recs[i].size()), d, l);
      }
      if (rc != 0) {
        err = "pipeline: decode failed (rc=" + std::to_string(rc) + ")";
        break;
      }
    }
    // Any slots not covered by records (only possible when the whole
    // epoch has fewer than batch_size records) are zeroed.
    size_t filled = w.recs.size();
    if (filled < static_cast<size_t>(cfg_.batch_size) && err.empty()) {
      std::memset(b.data + filled * cfg_.sample_bytes, 0,
                  data_bytes_ - filled * cfg_.sample_bytes);
      std::memset(b.label + filled * cfg_.label_width, 0,
                  label_bytes_ - sizeof(float) * filled * cfg_.label_width);
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!err.empty() && error_.empty()) error_ = err;
      done_.emplace(b.seq, b);
      done_cv_.notify_all();
    }
  }
}

bool Pipeline::Next(Batch* out) {
  std::unique_lock<std::mutex> lk(mu_);
  done_cv_.wait(lk, [&] {
    return stop_.load() || !error_.empty() ||
           done_.count(next_out_) > 0 ||
           (io_done_ && work_q_.empty() && done_.empty() && outstanding_ == 0);
  });
  if (!error_.empty()) throw std::runtime_error(error_);
  if (stop_.load()) return false;
  auto it = done_.find(next_out_);
  if (it == done_.end()) return false;  // epoch exhausted
  *out = it->second;
  done_.erase(it);
  next_out_++;
  outstanding_--;
  space_cv_.notify_one();
  return true;
}

void Pipeline::Release(const Batch& b) {
  if (b.data) pool_.Free(b.data, data_bytes_);
  if (b.label) pool_.Free(b.label, label_bytes_);
}

}  // namespace mxtpu
