// C predict ABI (MXTPUPred*) — deployment surface for non-Python consumers.
//
// Reference: include/mxnet/c_predict_api.h (MXPredCreate/SetInput/Forward/
// GetOutputShape/GetOutput/Reshape/Free) backed by a self-contained C++
// inference engine.  TPU-native form: on TPU the inference runtime IS
// jax/XLA/PJRT, so instead of maintaining a second compute engine this ABI
// hosts a CPython interpreter (dlopen'd lazily, never a link-time
// dependency) and drives mxnet_tpu._predict_embed, which stages the
// exported graph through the same jit path Python users get.  All data
// crosses the boundary as raw addresses formatted into interpreter
// source — no CPython API types appear in this file, so libmxtpu builds
// with no Python headers.
//
// Two hosting modes:
//  * loaded into an existing Python process (ctypes): Py_IsInitialized()
//    is true; we only take the GIL around each call.
//  * linked/dlopen'd from a plain C program: first call initializes the
//    interpreter; MXTPU_PYTHONPATH (colon-separated) is appended to
//    sys.path so the venv's jax and this package resolve.
#include <dlfcn.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace {

constexpr int kMaxNdim = 16;
constexpr int kErrCap = 8192;

// -------------------------------------------------------- libpython glue --
typedef int (*Fn_IsInitialized)();
typedef void (*Fn_InitializeEx)(int);
typedef int (*Fn_GILEnsure)();
typedef void (*Fn_GILRelease)(int);
typedef void* (*Fn_SaveThread)();
typedef int (*Fn_RunSimpleString)(const char*);

struct PyRuntime {
  Fn_IsInitialized is_initialized = nullptr;
  Fn_InitializeEx initialize_ex = nullptr;
  Fn_GILEnsure gil_ensure = nullptr;
  Fn_GILRelease gil_release = nullptr;
  Fn_SaveThread save_thread = nullptr;
  Fn_RunSimpleString run_simple_string = nullptr;
  bool ok = false;
  std::string error;
};

PyRuntime* LoadPyRuntime() {
  static PyRuntime rt;
  static std::once_flag once;
  std::call_once(once, []() {
    void* h = dlopen(nullptr, RTLD_NOW | RTLD_GLOBAL);  // host process first
    if (!h || !dlsym(h, "Py_IsInitialized")) {
      const char* env = getenv("MXTPU_LIBPYTHON");
      std::vector<std::string> names;
      if (env && env[0]) names.push_back(env);
      for (const char* n :
           {"libpython3.12.so.1.0", "libpython3.13.so.1.0",
            "libpython3.11.so.1.0", "libpython3.10.so.1.0", "libpython3.so"})
        names.push_back(n);
      h = nullptr;
      for (const auto& n : names) {
        h = dlopen(n.c_str(), RTLD_NOW | RTLD_GLOBAL);
        if (h && dlsym(h, "Py_IsInitialized")) break;
        h = nullptr;
      }
    }
    if (!h) {
      rt.error = "MXTPUPred: cannot locate libpython (set MXTPU_LIBPYTHON)";
      return;
    }
    rt.is_initialized = (Fn_IsInitialized)dlsym(h, "Py_IsInitialized");
    rt.initialize_ex = (Fn_InitializeEx)dlsym(h, "Py_InitializeEx");
    rt.gil_ensure = (Fn_GILEnsure)dlsym(h, "PyGILState_Ensure");
    rt.gil_release = (Fn_GILRelease)dlsym(h, "PyGILState_Release");
    rt.save_thread = (Fn_SaveThread)dlsym(h, "PyEval_SaveThread");
    rt.run_simple_string = (Fn_RunSimpleString)dlsym(h, "PyRun_SimpleString");
    if (!rt.is_initialized || !rt.initialize_ex || !rt.gil_ensure ||
        !rt.gil_release || !rt.save_thread || !rt.run_simple_string) {
      rt.error = "MXTPUPred: libpython found but symbols missing";
      return;
    }
    if (!rt.is_initialized()) {
      rt.initialize_ex(0);
      // Make the venv / repo importable inside the embedded interpreter.
      rt.run_simple_string(
          "import sys, os\n"
          "for _p in reversed(os.environ.get('MXTPU_PYTHONPATH', '')"
          ".split(':')):\n"
          "    if _p and _p not in sys.path:\n"
          "        sys.path.insert(0, _p)\n");
      rt.save_thread();  // release the GIL; every call re-takes it
    }
    rt.ok = true;
  });
  return &rt;
}

// One embedded call: format source invoking _predict_embed.<fn>(args...),
// run it under the GIL, surface (status, errbuf) back as a C++ exception.
struct CallBuf {
  int64_t status = -2;
  char err[kErrCap];
  CallBuf() { err[0] = '\0'; }
};

void EmbedCall(const std::string& fn, const std::string& args) {
  PyRuntime* rt = LoadPyRuntime();
  if (!rt->ok) throw std::runtime_error(rt->error);
  CallBuf buf;
  // All sources share __main__'s globals; name temporaries after this
  // call's stack buffer so concurrent failing calls on other threads
  // can't cross-contaminate error buffers between statements.
  unsigned long long uniq = (unsigned long long)(uintptr_t)&buf;
  char src[1280];
  std::snprintf(src, sizeof(src),
                "try:\n"
                "    import mxnet_tpu._predict_embed as _pe\n"
                "    _pe.%s(%s%s%llu, %llu, %d)\n"
                "except BaseException:\n"
                "    import ctypes as _ct_%llx, traceback as _tb_%llx\n"
                "    _m_%llx = _tb_%llx.format_exc().encode()[:%d] + b'\\0'\n"
                "    _ct_%llx.memmove(%llu, _m_%llx, len(_m_%llx))\n"
                "    _ct_%llx.cast(%llu, _ct_%llx.POINTER("
                "_ct_%llx.c_int64))[0] = -1\n",
                fn.c_str(), args.c_str(), args.empty() ? "" : ", ",
                (unsigned long long)(uintptr_t)&buf.status,
                (unsigned long long)(uintptr_t)buf.err, kErrCap - 1, uniq,
                uniq, uniq, uniq, kErrCap - 1, uniq,
                (unsigned long long)(uintptr_t)buf.err, uniq, uniq, uniq,
                (unsigned long long)(uintptr_t)&buf.status, uniq, uniq);
  int gil = rt->gil_ensure();
  int rc = rt->run_simple_string(src);
  rt->gil_release(gil);
  if (rc != 0 && buf.status == -2)
    throw std::runtime_error("MXTPUPred: embedded interpreter failure in " +
                             fn + " (see stderr)");
  if (buf.status != 0)
    throw std::runtime_error(buf.err[0] ? buf.err
                                        : "MXTPUPred: " + fn + " failed");
}

struct Predictor {
  uint64_t id = 0;
  uint32_t out_shape[1 + kMaxNdim];  // [ndim, dims...] scratch
};

std::string ShapeArgs(uint32_t num, const char** keys, const uint32_t* indptr,
                      const uint32_t* shapes) {
  char a[256];
  std::snprintf(a, sizeof(a), "%u, %llu, %llu, %llu", num,
                (unsigned long long)(uintptr_t)keys,
                (unsigned long long)(uintptr_t)indptr,
                (unsigned long long)(uintptr_t)shapes);
  return a;
}

}  // namespace

MXTPU_EXPORT int MXTPUPredCreate(const char* symbol_json,
                                 const void* param_bytes, uint64_t param_size,
                                 int dev_type, int dev_id,
                                 uint32_t num_input_nodes,
                                 const char** input_keys,
                                 const uint32_t* input_shape_indptr,
                                 const uint32_t* input_shape_data,
                                 void** out) {
  MXTPU_API_BEGIN();
  uint64_t pid = 0;
  char a[512];
  std::snprintf(a, sizeof(a), "%llu, %llu, %llu, %llu, %d, %d, %s, %llu",
                (unsigned long long)(uintptr_t)symbol_json,
                (unsigned long long)strlen(symbol_json),
                (unsigned long long)(uintptr_t)param_bytes,
                (unsigned long long)param_size, dev_type, dev_id,
                ShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                          input_shape_data)
                    .c_str(),
                (unsigned long long)(uintptr_t)&pid);
  EmbedCall("c_create", a);
  auto* p = new Predictor();
  p->id = pid;
  *out = p;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredSetInput(void* handle, const char* key,
                                   const float* data, uint64_t size) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[256];
  std::snprintf(a, sizeof(a), "%llu, %llu, %llu, %llu",
                (unsigned long long)p->id,
                (unsigned long long)(uintptr_t)key,
                (unsigned long long)(uintptr_t)data,
                (unsigned long long)size);
  EmbedCall("c_set_input", a);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredForward(void* handle) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  EmbedCall("c_forward", std::to_string(p->id));
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredGetOutputShape(void* handle, uint32_t index,
                                         const uint32_t** shape_data,
                                         uint32_t* shape_ndim) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[128];
  std::snprintf(a, sizeof(a), "%llu, %u, %llu", (unsigned long long)p->id,
                index, (unsigned long long)(uintptr_t)p->out_shape);
  EmbedCall("c_get_output_shape", a);
  *shape_ndim = p->out_shape[0];
  *shape_data = p->out_shape + 1;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredGetOutput(void* handle, uint32_t index, float* data,
                                    uint64_t size) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[160];
  std::snprintf(a, sizeof(a), "%llu, %u, %llu, %llu",
                (unsigned long long)p->id, index,
                (unsigned long long)(uintptr_t)data, (unsigned long long)size);
  EmbedCall("c_get_output", a);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredReshape(uint32_t num_input_nodes,
                                  const char** input_keys,
                                  const uint32_t* input_shape_indptr,
                                  const uint32_t* input_shape_data,
                                  void* handle, void** out) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  uint64_t nid = 0;
  char a[384];
  std::snprintf(a, sizeof(a), "%llu, %s, %llu", (unsigned long long)p->id,
                ShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                          input_shape_data)
                    .c_str(),
                (unsigned long long)(uintptr_t)&nid);
  EmbedCall("c_reshape", a);
  auto* np = new Predictor();
  np->id = nid;
  *out = np;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredFree(void* handle) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  if (p->id) EmbedCall("c_free", std::to_string(p->id));
  delete p;
  MXTPU_API_END();
}
