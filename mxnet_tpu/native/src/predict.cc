// C predict ABI (MXTPUPred*) — deployment surface for non-Python consumers.
//
// Reference: include/mxnet/c_predict_api.h (MXPredCreate/SetInput/Forward/
// GetOutputShape/GetOutput/Reshape/Free) backed by a self-contained C++
// inference engine.  TPU-native form: the embedded-interpreter bridge
// (embed.h) drives mxnet_tpu._predict_embed, which stages the exported
// graph through the same jit path Python users get.
#include <cstdio>
#include <cstring>
#include <string>

#include "../include/mxtpu/c_predict_api.h"  // compiler-checked ABI decls
#include "common.h"
#include "embed.h"

namespace {

constexpr int kMaxNdim = 16;

void PredCall(const std::string& fn, const std::string& args) {
  mxtpu::EmbedCall("_predict_embed", fn.c_str(), args);
}

struct Predictor {
  uint64_t id = 0;
  uint32_t out_shape[1 + kMaxNdim];  // [ndim, dims...] scratch
};

std::string ShapeArgs(uint32_t num, const char** keys, const uint32_t* indptr,
                      const uint32_t* shapes) {
  char a[256];
  std::snprintf(a, sizeof(a), "%u, %llu, %llu, %llu", num,
                (unsigned long long)(uintptr_t)keys,
                (unsigned long long)(uintptr_t)indptr,
                (unsigned long long)(uintptr_t)shapes);
  return a;
}

}  // namespace

MXTPU_EXPORT int MXTPUPredCreate(const char* symbol_json,
                                 const void* param_bytes, uint64_t param_size,
                                 int dev_type, int dev_id,
                                 uint32_t num_input_nodes,
                                 const char** input_keys,
                                 const uint32_t* input_shape_indptr,
                                 const uint32_t* input_shape_data,
                                 void** out) {
  MXTPU_API_BEGIN();
  uint64_t pid = 0;
  char a[512];
  std::snprintf(a, sizeof(a), "%llu, %llu, %llu, %llu, %d, %d, %s, %llu",
                (unsigned long long)(uintptr_t)symbol_json,
                (unsigned long long)strlen(symbol_json),
                (unsigned long long)(uintptr_t)param_bytes,
                (unsigned long long)param_size, dev_type, dev_id,
                ShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                          input_shape_data)
                    .c_str(),
                (unsigned long long)(uintptr_t)&pid);
  PredCall("c_create", a);
  auto* p = new Predictor();
  p->id = pid;
  *out = p;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredSetInput(void* handle, const char* key,
                                   const float* data, uint64_t size) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[256];
  std::snprintf(a, sizeof(a), "%llu, %llu, %llu, %llu",
                (unsigned long long)p->id,
                (unsigned long long)(uintptr_t)key,
                (unsigned long long)(uintptr_t)data,
                (unsigned long long)size);
  PredCall("c_set_input", a);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredForward(void* handle) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  PredCall("c_forward", std::to_string(p->id));
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredGetOutputShape(void* handle, uint32_t index,
                                         const uint32_t** shape_data,
                                         uint32_t* shape_ndim) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[128];
  std::snprintf(a, sizeof(a), "%llu, %u, %llu", (unsigned long long)p->id,
                index, (unsigned long long)(uintptr_t)p->out_shape);
  PredCall("c_get_output_shape", a);
  *shape_ndim = p->out_shape[0];
  *shape_data = p->out_shape + 1;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredGetOutput(void* handle, uint32_t index, float* data,
                                    uint64_t size) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  char a[160];
  std::snprintf(a, sizeof(a), "%llu, %u, %llu, %llu",
                (unsigned long long)p->id, index,
                (unsigned long long)(uintptr_t)data, (unsigned long long)size);
  PredCall("c_get_output", a);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredReshape(uint32_t num_input_nodes,
                                  const char** input_keys,
                                  const uint32_t* input_shape_indptr,
                                  const uint32_t* input_shape_data,
                                  void* handle, void** out) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  uint64_t nid = 0;
  char a[384];
  std::snprintf(a, sizeof(a), "%llu, %s, %llu", (unsigned long long)p->id,
                ShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                          input_shape_data)
                    .c_str(),
                (unsigned long long)(uintptr_t)&nid);
  PredCall("c_reshape", a);
  auto* np = new Predictor();
  np->id = nid;
  *out = np;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPredFree(void* handle) {
  MXTPU_API_BEGIN();
  auto* p = static_cast<Predictor*>(handle);
  if (p->id) PredCall("c_free", std::to_string(p->id));
  delete p;
  MXTPU_API_END();
}
