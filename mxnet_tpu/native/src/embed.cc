// Embedded-CPython bridge implementation (see embed.h).  Hosting modes:
//  * loaded into an existing Python process (ctypes): Py_IsInitialized()
//    is true; we only take the GIL around each call.
//  * linked/dlopen'd from a plain C program: first call initializes the
//    interpreter; MXTPU_PYTHONPATH (colon-separated) is appended to
//    sys.path so the venv's jax and this package resolve.
#include "embed.h"

#include <dlfcn.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <stdexcept>
#include <vector>

namespace mxtpu {
namespace {

constexpr int kErrCap = 8192;

typedef int (*Fn_IsInitialized)();
typedef void (*Fn_InitializeEx)(int);
typedef int (*Fn_GILEnsure)();
typedef void (*Fn_GILRelease)(int);
typedef void* (*Fn_SaveThread)();
typedef int (*Fn_RunSimpleString)(const char*);

struct PyRuntime {
  Fn_IsInitialized is_initialized = nullptr;
  Fn_InitializeEx initialize_ex = nullptr;
  Fn_GILEnsure gil_ensure = nullptr;
  Fn_GILRelease gil_release = nullptr;
  Fn_SaveThread save_thread = nullptr;
  Fn_RunSimpleString run_simple_string = nullptr;
  bool ok = false;
  std::string error;
};

PyRuntime* LoadPyRuntime() {
  static PyRuntime rt;
  static std::once_flag once;
  std::call_once(once, []() {
    void* h = dlopen(nullptr, RTLD_NOW | RTLD_GLOBAL);  // host process first
    if (!h || !dlsym(h, "Py_IsInitialized")) {
      const char* env = getenv("MXTPU_LIBPYTHON");
      std::vector<std::string> names;
      if (env && env[0]) names.push_back(env);
      for (const char* n :
           {"libpython3.12.so.1.0", "libpython3.13.so.1.0",
            "libpython3.11.so.1.0", "libpython3.10.so.1.0", "libpython3.so"})
        names.push_back(n);
      h = nullptr;
      for (const auto& n : names) {
        h = dlopen(n.c_str(), RTLD_NOW | RTLD_GLOBAL);
        if (h && dlsym(h, "Py_IsInitialized")) break;
        h = nullptr;
      }
    }
    if (!h) {
      rt.error = "mxtpu embed: cannot locate libpython (set MXTPU_LIBPYTHON)";
      return;
    }
    rt.is_initialized = (Fn_IsInitialized)dlsym(h, "Py_IsInitialized");
    rt.initialize_ex = (Fn_InitializeEx)dlsym(h, "Py_InitializeEx");
    rt.gil_ensure = (Fn_GILEnsure)dlsym(h, "PyGILState_Ensure");
    rt.gil_release = (Fn_GILRelease)dlsym(h, "PyGILState_Release");
    rt.save_thread = (Fn_SaveThread)dlsym(h, "PyEval_SaveThread");
    rt.run_simple_string = (Fn_RunSimpleString)dlsym(h, "PyRun_SimpleString");
    if (!rt.is_initialized || !rt.initialize_ex || !rt.gil_ensure ||
        !rt.gil_release || !rt.save_thread || !rt.run_simple_string) {
      rt.error = "mxtpu embed: libpython found but symbols missing";
      return;
    }
    if (!rt.is_initialized()) {
      rt.initialize_ex(0);
      // Make the venv / repo importable inside the embedded interpreter.
      rt.run_simple_string(
          "import sys, os\n"
          "for _p in reversed(os.environ.get('MXTPU_PYTHONPATH', '')"
          ".split(':')):\n"
          "    if _p and _p not in sys.path:\n"
          "        sys.path.insert(0, _p)\n");
      rt.save_thread();  // release the GIL; every call re-takes it
    }
    rt.ok = true;
  });
  return &rt;
}

struct CallBuf {
  int64_t status = -2;
  char err[kErrCap];
  CallBuf() { err[0] = '\0'; }
};

}  // namespace

EmbedArgs& EmbedArgs::p(const void* ptr) {
  return u((unsigned long long)(uintptr_t)ptr);
}

EmbedArgs& EmbedArgs::u(unsigned long long v) {
  Sep();
  char b[24];
  std::snprintf(b, sizeof(b), "%llu", v);
  s_ += b;
  return *this;
}

EmbedArgs& EmbedArgs::i(long long v) {
  Sep();
  char b[24];
  std::snprintf(b, sizeof(b), "%lld", v);
  s_ += b;
  return *this;
}

void EmbedArgs::Sep() {
  if (!s_.empty()) s_ += ", ";
}

void EmbedCall(const char* module, const char* fn, const std::string& args) {
  PyRuntime* rt = LoadPyRuntime();
  if (!rt->ok) throw std::runtime_error(rt->error);
  CallBuf buf;
  // All sources share __main__'s globals; name temporaries after this
  // call's stack buffer so concurrent failing calls on other threads
  // can't cross-contaminate error buffers between statements.
  unsigned long long uniq = (unsigned long long)(uintptr_t)&buf;
  char tail[768];
  std::snprintf(tail, sizeof(tail),
                "%s%llu, %llu, %d)\n"
                "except BaseException:\n"
                "    import ctypes as _ct_%llx, traceback as _tb_%llx\n"
                "    _m_%llx = _tb_%llx.format_exc().encode()[:%d] + b'\\0'\n"
                "    _ct_%llx.memmove(%llu, _m_%llx, len(_m_%llx))\n"
                "    _ct_%llx.cast(%llu, _ct_%llx.POINTER("
                "_ct_%llx.c_int64))[0] = -1\n",
                args.empty() ? "" : ", ",
                (unsigned long long)(uintptr_t)&buf.status,
                (unsigned long long)(uintptr_t)buf.err, kErrCap - 1, uniq,
                uniq, uniq, uniq, kErrCap - 1, uniq,
                (unsigned long long)(uintptr_t)buf.err, uniq, uniq, uniq,
                (unsigned long long)(uintptr_t)&buf.status, uniq, uniq);
  std::string src = std::string("try:\n    import mxnet_tpu.") + module +
                    " as _pe\n    _pe." + fn + "(" + args + tail;
  int gil = rt->gil_ensure();
  int rc = rt->run_simple_string(src.c_str());
  rt->gil_release(gil);
  if (rc != 0 && buf.status == -2)
    throw std::runtime_error(
        std::string("mxtpu embed: interpreter failure in ") + fn +
        " (see stderr)");
  if (buf.status != 0)
    throw std::runtime_error(buf.err[0]
                                 ? std::string(buf.err)
                                 : std::string("mxtpu embed: ") + fn +
                                       " failed");
}

}  // namespace mxtpu
