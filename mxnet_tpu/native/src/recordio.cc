#include "recordio.h"

#include <sys/stat.h>

#include <algorithm>
#include <cstring>
#include <stdexcept>

#include "common.h"

namespace mxtpu {

static size_t FileSize(const std::string& path) {
  struct stat st;
  if (stat(path.c_str(), &st) != 0)
    throw std::runtime_error("recordio: cannot stat " + path);
  return static_cast<size_t>(st.st_size);
}

RecordReader::RecordReader(const std::string& path, size_t chunk_bytes,
                           int part_index, int num_parts)
    : path_(path), chunk_(chunk_bytes ? chunk_bytes : (8u << 20)) {
  f_ = fopen(path.c_str(), "rb");
  if (!f_) throw std::runtime_error("recordio: cannot open " + path);
  size_t size = FileSize(path);
  sharded_ = num_parts > 1;
  num_parts_ = num_parts;
  if (num_parts <= 1) {
    begin_ = 0;
    end_ = size;
  } else {
    size_t lo = size * part_index / num_parts;
    size_t hi = size * (part_index + 1) / num_parts;
    begin_ = SeekBoundary(lo);
    end_ = (part_index + 1 == num_parts) ? size : SeekBoundary(hi);
  }
  Reset();
}

RecordReader::~RecordReader() {
  if (f_) fclose(f_);
}

size_t RecordReader::SeekBoundary(size_t pos) {
  // Records are 4-byte aligned and start with the magic word; scan aligned
  // words until magic found and the length field is plausible.
  size_t size = FileSize(path_);
  pos = (pos + 3) & ~size_t(3);
  std::vector<uint8_t> win(1 << 16);
  while (pos < size) {
    if (fseek(f_, static_cast<long>(pos), SEEK_SET) != 0) break;
    size_t got = fread(win.data(), 1, win.size(), f_);
    for (size_t i = 0; i + 8 <= got; i += 4) {
      uint32_t magic, len;
      std::memcpy(&magic, &win[i], 4);
      std::memcpy(&len, &win[i + 4], 4);
      if (magic == kRecMagic && pos + i + 8 + len <= size) return pos + i;
    }
    pos += got > 8 ? got - 8 : got;  // overlap so a boundary on the edge isn't missed
    if (got < win.size()) break;
  }
  return size;
}

void RecordReader::Reset() {
  file_pos_ = begin_;
  buf_off_ = buf_len_ = 0;
  if (fseek(f_, static_cast<long>(begin_), SEEK_SET) != 0)
    throw std::runtime_error("recordio: seek failed in " + path_);
}

void RecordReader::Seek(uint64_t pos) {
  // Random access (.idx offsets are whole-file) and byte-range sharding
  // (sequential) are different access patterns; mixing them would let a
  // part-k reader return records another shard owns.
  if (sharded_)
    throw std::runtime_error(
        "recordio: Seek is only supported on unsharded readers (" + path_ +
        " was opened as part of " + std::to_string(num_parts_) + ")");
  if (pos > end_)
    throw std::runtime_error("recordio: seek past end of file in " + path_);
  file_pos_ = static_cast<size_t>(pos);
  buf_off_ = buf_len_ = 0;
  if (fseek(f_, static_cast<long>(file_pos_), SEEK_SET) != 0)
    throw std::runtime_error("recordio: seek failed in " + path_);
}

void RecordReader::FillBuffer() {
  // Move unconsumed tail to front, then read one chunk.
  size_t tail = buf_len_ - buf_off_;
  if (buf_.size() < chunk_ + tail) buf_.resize(chunk_ + tail);
  if (tail && buf_off_) std::memmove(buf_.data(), buf_.data() + buf_off_, tail);
  buf_off_ = 0;
  buf_len_ = tail;
  size_t want = std::min(chunk_, end_ - file_pos_);
  if (want == 0) return;
  size_t got = fread(buf_.data() + buf_len_, 1, want, f_);
  file_pos_ += got;
  buf_len_ += got;
}

bool RecordReader::NextRecord(const uint8_t** data, uint32_t* size) {
  if (buf_len_ - buf_off_ < 8) {
    FillBuffer();
    if (buf_len_ - buf_off_ < 8) return false;  // end of shard
  }
  uint32_t magic, len;
  std::memcpy(&magic, buf_.data() + buf_off_, 4);
  std::memcpy(&len, buf_.data() + buf_off_ + 4, 4);
  if (magic != kRecMagic)
    throw std::runtime_error("recordio: bad magic in " + path_);
  size_t need = 8 + len + ((4 - len % 4) % 4);
  while (buf_len_ - buf_off_ < need) {
    size_t before = buf_len_ - buf_off_;
    FillBuffer();
    if (buf_len_ - buf_off_ == before)
      throw std::runtime_error("recordio: truncated record in " + path_);
  }
  *data = buf_.data() + buf_off_ + 8;
  *size = len;
  buf_off_ += need;
  return true;
}

RecordWriter::RecordWriter(const std::string& path) {
  f_ = fopen(path.c_str(), "wb");
  if (!f_) throw std::runtime_error("recordio: cannot open for write " + path);
}

RecordWriter::~RecordWriter() {
  if (f_) fclose(f_);
}

uint64_t RecordWriter::Write(const uint8_t* data, uint32_t size) {
  uint64_t at = pos_;
  uint32_t head[2] = {kRecMagic, size};
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  if (fwrite(head, 1, 8, f_) != 8 ||
      fwrite(data, 1, size, f_) != size)
    throw std::runtime_error("recordio: write failed");
  uint32_t pad = (4 - size % 4) % 4;
  if (pad && fwrite(zeros, 1, pad, f_) != pad)
    throw std::runtime_error("recordio: write failed");
  pos_ += 8 + size + pad;
  return at;
}

void RecordWriter::Flush() { fflush(f_); }

}  // namespace mxtpu
