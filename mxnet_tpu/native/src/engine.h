// Async dependency engine for host-side work.
//
// A TPU-native re-design of the reference's ThreadedEngine
// (src/engine/threaded_engine.h:66-269, threaded_engine_perdevice.cc:46):
// versioned variables hold FIFO queues of pending reader/writer ops; an op
// dispatches once every dependency is satisfied.  On TPU the device-side
// scheduling this engine did for CUDA ops is owned by XLA's async runtime,
// so this engine schedules the HOST side: data-pipeline stages, checkpoint
// writes, metric syncs, custom Python ops — while preserving the reference's
// semantics (read sharing, write exclusivity, version ordering, exception
// propagation to WaitForVar, FnProperty queues).
#ifndef MXTPU_ENGINE_H_
#define MXTPU_ENGINE_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace mxtpu {

// Mirrors reference FnProperty (include/mxnet/engine.h:73): which worker
// pool an op runs on.  kAsync ops complete via an explicit callback.
enum class FnProperty : int {
  kNormal = 0,
  kIO = 1,        // data-pipeline / disk work
  kPriority = 2,  // latency-critical (parameter fetch)
  kAsync = 3,     // completes out-of-band (e.g. Python callback thread)
};

class Engine;

// A versioned variable (reference ThreadedVar, threaded_engine.h:115).
// Pending ops queue on the var; reads share, writes are exclusive and
// bump the version.
struct Var {
  uint64_t id;
  uint64_t version{0};

  // Dependency queue state (guarded by Engine::mu_ for simplicity; the
  // reference uses a per-var spinlock — host-side op rates here are far
  // below device-op rates, so one mutex is the better trade).
  struct PendingOp;
  std::deque<PendingOp*> queue;
  int running_reads{0};
  bool running_write{false};
  // First error produced by an op that wrote this var; re-thrown at
  // WaitForVar (reference: threaded_engine.h:179 exception_ptr).
  std::shared_ptr<std::string> error;
  // Set by DeleteVariable's marker op; CompleteOp erases the var once no
  // access is running and nothing is queued.
  bool to_delete{false};

  explicit Var(uint64_t i) : id(i) {}
};

// An operation pushed to the engine (reference ThreadedOpr/OprBlock).
struct Op {
  std::function<void(Engine*, uint64_t op_id)> fn;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  FnProperty prop{FnProperty::kNormal};
  std::string name;
  uint64_t id{0};
  std::atomic<int> wait_count{0};
  bool temporary{true};  // delete after run (PushAsync one-shot)
};

struct Var::PendingOp {
  Op* op;
  bool is_write;
};

class Engine {
 public:
  // n_workers: kNormal pool size; io_workers: kIO pool; one kPriority worker.
  // (reference env vars MXNET_CPU_WORKER_NTHREADS etc.)
  Engine(int n_workers, int io_workers);
  ~Engine();

  uint64_t NewVariable();
  // Schedules var deletion after all pending ops on it complete
  // (reference: ThreadedEngine::DeleteVariable).
  void DeleteVariable(uint64_t var);

  // Push fn with dependencies; fn runs on a worker once deps resolve.
  // Returns op id.  Read/write sets must be disjoint.
  uint64_t PushAsync(std::function<void(Engine*, uint64_t)> fn,
                     const std::vector<uint64_t>& const_vars,
                     const std::vector<uint64_t>& mutable_vars,
                     FnProperty prop, const std::string& name);

  // For kAsync ops: mark op complete from an external thread.
  void OnComplete(uint64_t op_id);
  // Record an error for the op's mutable vars, then complete it.
  void OnCompleteError(uint64_t op_id, const std::string& msg);

  // Block until all ops writing `var` (pushed before this call) are done.
  // Throws if any writer recorded an error (reference: WaitForVar rethrow).
  void WaitForVar(uint64_t var);
  void WaitForAll();

  int64_t num_pending() const { return pending_.load(); }

 private:
  struct Worker;

  Var* GetVar(uint64_t id);
  void Schedule(Op* op);            // deps resolved -> queue to pool
  void Enqueue(Op* op);             // push to the right worker queue
  void RunOp(Op* op);
  void CompleteOp(Op* op, const std::string* err);
  // With mu_ held: try to start next pending ops on var.
  void DrainVar(Var* v);
  void DependOn(Op* op, Var* v, bool write);

  mutable std::mutex mu_;
  std::condition_variable all_done_;
  std::unordered_map<uint64_t, std::unique_ptr<Var>> vars_;
  std::unordered_map<uint64_t, Op*> inflight_;  // kAsync ops awaiting OnComplete
  uint64_t next_var_{1};
  uint64_t next_op_{1};
  std::atomic<int64_t> pending_{0};
  // Ops made ready by the current CompleteOp (with mu_ held); swapped out
  // and enqueued after the lock is released.
  std::vector<Op*> ready_scratch_;

  // Worker pools.
  struct Pool {
    std::deque<Op*> q;
    std::mutex mu;
    std::condition_variable cv;
    std::vector<std::thread> threads;
  };
  Pool normal_, io_, priority_;
  std::atomic<bool> shutdown_{false};

  void WorkerLoop(Pool* pool);
};

}  // namespace mxtpu

#endif  // MXTPU_ENGINE_H_
