// See engine.h.  Semantics mirror the reference ThreadedEngine
// (src/engine/threaded_engine.{h,cc}): per-var FIFO of pending ops, reads
// share / writes exclusive, op fires when wait_count hits zero, errors
// propagate to WaitForVar on the written vars.
#include "engine.h"

#include <cassert>

namespace mxtpu {

Engine::Engine(int n_workers, int io_workers) {
  if (n_workers < 1) n_workers = 1;
  if (io_workers < 1) io_workers = 1;
  for (int i = 0; i < n_workers; ++i)
    normal_.threads.emplace_back([this] { WorkerLoop(&normal_); });
  for (int i = 0; i < io_workers; ++i)
    io_.threads.emplace_back([this] { WorkerLoop(&io_); });
  priority_.threads.emplace_back([this] { WorkerLoop(&priority_); });
}

Engine::~Engine() {
  try {
    WaitForAll();
  } catch (...) {
  }
  shutdown_.store(true);
  for (Pool* p : {&normal_, &io_, &priority_}) {
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->cv.notify_all();
    }
    for (auto& t : p->threads) t.join();
  }
}

void Engine::WorkerLoop(Pool* pool) {
  for (;;) {
    Op* op = nullptr;
    {
      std::unique_lock<std::mutex> lk(pool->mu);
      pool->cv.wait(lk, [&] { return shutdown_.load() || !pool->q.empty(); });
      if (pool->q.empty()) return;  // shutdown
      op = pool->q.front();
      pool->q.pop_front();
    }
    RunOp(op);
  }
}

uint64_t Engine::NewVariable() {
  std::lock_guard<std::mutex> lk(mu_);
  uint64_t id = next_var_++;
  vars_.emplace(id, std::unique_ptr<Var>(new Var(id)));
  return id;
}

Var* Engine::GetVar(uint64_t id) {
  auto it = vars_.find(id);
  if (it == vars_.end()) throw std::runtime_error("engine: unknown var");
  return it->second.get();
}

void Engine::DeleteVariable(uint64_t var) {
  // Push a write op that only MARKS the var; CompleteOp erases it after it
  // finishes touching the Var (erasing inline would free the Var while
  // CompleteOp still dereferences it).  All earlier ops on the var are
  // ordered before the marking write.
  PushAsync(
      [this, var](Engine*, uint64_t) {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = vars_.find(var);
        if (it != vars_.end()) it->second->to_delete = true;
      },
      {}, {var}, FnProperty::kPriority, "delete_var");
}

void Engine::DependOn(Op* op, Var* v, bool write) {
  // Called with mu_ held.  If the var is free for this access now, and
  // nothing is queued ahead, take it; otherwise enqueue.
  bool can_run_now =
      v->queue.empty() &&
      (write ? (!v->running_write && v->running_reads == 0)
             : !v->running_write);
  if (can_run_now) {
    if (write)
      v->running_write = true;
    else
      v->running_reads++;
  } else {
    v->queue.push_back(new Var::PendingOp{op, write});
    op->wait_count.fetch_add(1);
  }
}

uint64_t Engine::PushAsync(std::function<void(Engine*, uint64_t)> fn,
                           const std::vector<uint64_t>& const_vars,
                           const std::vector<uint64_t>& mutable_vars,
                           FnProperty prop, const std::string& name) {
  std::unique_ptr<Op> guard(new Op());
  Op* op = guard.get();
  op->fn = std::move(fn);
  op->prop = prop;
  op->name = name;
  bool ready;
  {
    std::lock_guard<std::mutex> lk(mu_);
    // Resolve every var id BEFORE touching any dependency state, so an
    // unknown id throws without leaking read/write shares or pending_.
    for (uint64_t v : const_vars) op->const_vars.push_back(GetVar(v));
    for (uint64_t v : mutable_vars) op->mutable_vars.push_back(GetVar(v));
    op->id = next_op_++;
    pending_.fetch_add(1);
    op->wait_count.store(1);  // guard: resolved after all DependOn calls
    for (Var* var : op->const_vars) DependOn(op, var, /*write=*/false);
    for (Var* var : op->mutable_vars) DependOn(op, var, /*write=*/true);
    ready = op->wait_count.fetch_sub(1) == 1;
    guard.release();  // ownership passes to the engine (freed in CompleteOp)
  }
  if (ready) Enqueue(op);
  return op->id;
}

void Engine::Enqueue(Op* op) {
  Pool* pool = &normal_;
  if (op->prop == FnProperty::kIO)
    pool = &io_;
  else if (op->prop == FnProperty::kPriority)
    pool = &priority_;
  // kAsync runs its body on the normal pool; completion comes via OnComplete.
  std::lock_guard<std::mutex> lk(pool->mu);
  pool->q.push_back(op);
  pool->cv.notify_one();
}

void Engine::RunOp(Op* op) {
  if (op->prop == FnProperty::kAsync) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      inflight_[op->id] = op;
    }
    try {
      op->fn(this, op->id);  // initiates; completion via OnComplete(op_id)
    } catch (const std::exception& e) {
      OnCompleteError(op->id, e.what());
    }
    return;
  }
  std::string err;
  bool failed = false;
  try {
    op->fn(this, op->id);
  } catch (const std::exception& e) {
    failed = true;
    err = e.what();
  } catch (...) {
    failed = true;
    err = "unknown error in engine op '" + op->name + "'";
  }
  CompleteOp(op, failed ? &err : nullptr);
}

void Engine::OnComplete(uint64_t op_id) {
  Op* op;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = inflight_.find(op_id);
    if (it == inflight_.end())
      throw std::runtime_error("engine: OnComplete for unknown op");
    op = it->second;
    inflight_.erase(it);
  }
  CompleteOp(op, nullptr);
}

void Engine::OnCompleteError(uint64_t op_id, const std::string& msg) {
  Op* op;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = inflight_.find(op_id);
    if (it == inflight_.end())
      throw std::runtime_error("engine: OnCompleteError for unknown op");
    op = it->second;
    inflight_.erase(it);
  }
  CompleteOp(op, &msg);
}

void Engine::CompleteOp(Op* op, const std::string* err) {
  std::vector<Op*> to_run;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (Var* v : op->const_vars) {
      v->running_reads--;
      DrainVar(v);
    }
    for (Var* v : op->mutable_vars) {
      v->running_write = false;
      v->version++;
      if (err)
        v->error = std::make_shared<std::string>(*err);
      else
        v->error.reset();  // a clean write clears a stale error
      DrainVar(v);
      if (v->to_delete && v->queue.empty() && !v->running_write &&
          v->running_reads == 0)
        vars_.erase(v->id);  // frees v; must be the last touch
    }
    // Collect ops that became ready (wait_count for them was decremented
    // inside DrainVar via the ready_ops_ scratch).
    to_run.swap(ready_scratch_);
  }
  delete op;
  for (Op* r : to_run) Enqueue(r);
  if (pending_.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lk(mu_);
    all_done_.notify_all();
  }
}

void Engine::DrainVar(Var* v) {
  // With mu_ held: start queued accesses in FIFO order — a run of reads
  // shares, a write is exclusive (reference ThreadedVar::CompleteReadDependency
  // / CompleteWriteDependency logic).
  while (!v->queue.empty()) {
    Var::PendingOp* p = v->queue.front();
    if (p->is_write) {
      if (v->running_write || v->running_reads > 0) break;
      v->running_write = true;
    } else {
      if (v->running_write) break;
      v->running_reads++;
    }
    v->queue.pop_front();
    if (p->op->wait_count.fetch_sub(1) == 1) ready_scratch_.push_back(p->op);
    delete p;
    if (v->running_write) break;  // write is exclusive; stop draining
  }
}

void Engine::WaitForVar(uint64_t var) {
  // Push a read op that signals a local latch, then wait on it.
  std::mutex m;
  std::condition_variable cv;
  bool done = false;
  std::shared_ptr<std::string> err;
  {
    std::lock_guard<std::mutex> lk(mu_);
    GetVar(var);  // validate
  }
  PushAsync(
      [&](Engine*, uint64_t) {
        std::lock_guard<std::mutex> lk(m);
        done = true;
        cv.notify_all();
      },
      {var}, {}, FnProperty::kPriority, "wait_for_var");
  std::unique_lock<std::mutex> lk(m);
  cv.wait(lk, [&] { return done; });
  {
    std::lock_guard<std::mutex> elk(mu_);
    auto it = vars_.find(var);
    if (it != vars_.end()) err = it->second->error;
  }
  if (err) throw std::runtime_error(*err);
}

void Engine::WaitForAll() {
  std::unique_lock<std::mutex> lk(mu_);
  all_done_.wait(lk, [&] { return pending_.load() == 0; });
}

}  // namespace mxtpu
