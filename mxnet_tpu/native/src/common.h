// Common helpers for the native runtime.
//
// TPU-native runtime layer: the device side (compute, memory planning,
// fusion) belongs to XLA; what stays native is the HOST side the reference
// implements in C++ — an async dependency engine for host-side work
// (reference: src/engine/threaded_engine.h), RecordIO data IO
// (reference: src/io/, dmlc-core recordio), a prefetching batch pipeline
// (reference: src/io/iter_prefetcher.h), and a recycled buffer pool
// (reference: src/storage/ CPU managers).
#ifndef MXTPU_COMMON_H_
#define MXTPU_COMMON_H_

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>

#define MXTPU_EXPORT extern "C" __attribute__((visibility("default")))

namespace mxtpu {

// Thread-local last-error string (reference: src/c_api/c_api_error.cc).
void SetLastError(const std::string& msg);
const char* GetLastError();

}  // namespace mxtpu

// Wrap a C-ABI body: catch exceptions, record message, return -1 on error.
#define MXTPU_API_BEGIN() try {
#define MXTPU_API_END()                        \
  }                                            \
  catch (const std::exception& e) {            \
    mxtpu::SetLastError(e.what());             \
    return -1;                                 \
  }                                            \
  catch (...) {                                \
    mxtpu::SetLastError("unknown C++ error");  \
    return -1;                                 \
  }                                            \
  return 0;

#endif  // MXTPU_COMMON_H_
