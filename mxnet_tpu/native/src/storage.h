// Recycled aligned host-buffer pool (reference: src/storage/ CPU storage
// managers, storage.cc:62-115 — pooled managers keyed by size).  Staging
// buffers for batch assembly are allocated once and recycled, so the
// steady-state data pipeline does no malloc.
#ifndef MXTPU_STORAGE_H_
#define MXTPU_STORAGE_H_

#include <cstdlib>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace mxtpu {

class BufferPool {
 public:
  ~BufferPool() {
    for (auto& kv : free_)
      for (void* p : kv.second) std::free(p);
  }

  void* Alloc(size_t size) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(size);
      if (it != free_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        return p;
      }
    }
    void* p = nullptr;
    // 64-byte alignment: cache lines + efficient dma_map on host→HBM copies.
    if (posix_memalign(&p, 64, size ? size : 64) != 0) return nullptr;
    return p;
  }

  void Free(void* p, size_t size) {
    std::lock_guard<std::mutex> lk(mu_);
    free_[size].push_back(p);
  }

 private:
  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void*>> free_;
};

}  // namespace mxtpu

#endif  // MXTPU_STORAGE_H_
