// Prefetching batch pipeline over RecordIO.
//
// TPU-native redesign of the reference's data path
// (src/io/iter_image_recordio_2.cc ImageRecordIOParser2 +
// iter_batchloader.h BatchLoader + iter_prefetcher.h PrefetcherIter):
// one IO thread does chunked sharded RecordIO reads and shuffle-buffer
// sampling; a decode worker pool fills preallocated batch buffers (via a
// user decode callback — e.g. Python JPEG decode — or a built-in raw
// decoder); completed batches flow through a bounded reorder queue so
// consumers see deterministic order.  Buffers recycle through BufferPool,
// so steady state is malloc-free; the consumer hands each buffer back
// after the host→HBM transfer.
#ifndef MXTPU_PIPELINE_H_
#define MXTPU_PIPELINE_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "recordio.h"
#include "storage.h"

namespace mxtpu {

// Decode one record into one sample slot.  Returns 0 on success.
// data_out has sample_bytes bytes; label_out has label_width floats.
typedef int (*DecodeFn)(void* ctx, const uint8_t* rec, uint32_t len,
                        uint8_t* data_out, float* label_out);

struct PipelineConfig {
  std::string path;
  size_t chunk_bytes = 8u << 20;
  int part_index = 0;
  int num_parts = 1;
  int batch_size = 32;
  size_t sample_bytes = 0;   // bytes per decoded sample
  int label_width = 1;
  int shuffle = 0;           // shuffle-buffer size in records; 0 = off
  uint64_t seed = 0;
  int num_workers = 4;
  int queue_depth = 0;       // 0 -> 2*num_workers
  int last_batch_keep = 1;   // keep partial final batch (count < batch_size)
  DecodeFn decode = nullptr; // null -> built-in raw decoder
  void* decode_ctx = nullptr;
  // built-in JPEG decode+augment (zero Python in the worker loop);
  // active when decode == nullptr and builtin_jpeg != 0.  Mirrors the
  // python _augment chain: decode -> random/center crop-or-pad to
  // (img_h, img_w) -> optional mirror -> float32 CHW minus mean.
  int builtin_jpeg = 0;
  DecodeFn jpeg_fallback = nullptr;  // called for non-JPEG payloads
  int img_h = 0, img_w = 0, img_c = 3;
  int rand_crop = 0;
  int rand_mirror = 0;
  float mean[3] = {0.f, 0.f, 0.f};
};

struct Batch {
  uint8_t* data{nullptr};   // batch_size * sample_bytes
  float* label{nullptr};    // batch_size * label_width
  int count{0};
  uint64_t seq{0};
};

class Pipeline {
 public:
  explicit Pipeline(const PipelineConfig& cfg);
  ~Pipeline();

  // Blocks for the next batch.  Returns false at end of epoch (no batch).
  bool Next(Batch* out);
  // Return a batch's buffers to the pool.
  void Release(const Batch& b);
  // Rewind to the start of the shard for a new epoch.
  void Reset();

 private:
  struct Work {                       // one undecoded batch
    std::vector<std::vector<uint8_t>> recs;
    uint64_t seq;
    int real_count{-1};  // <recs.size() when tail was padded by wrapping
  };

  void IoLoop();
  void DecodeLoop(int worker_idx);
  void PushDone(Batch b);
  void StopThreads();
  void StartThreads();
  int DecodeRaw(const uint8_t* rec, uint32_t len, uint8_t* data, float* label);
  int DecodeJpeg(const uint8_t* rec, uint32_t len, uint8_t* data,
                 float* label, std::mt19937* rng);
  int ParseHeader(const uint8_t* rec, uint32_t len, float* label,
                  const uint8_t** payload, size_t* payload_len);

  PipelineConfig cfg_;
  size_t data_bytes_, label_bytes_;
  BufferPool pool_;
  std::unique_ptr<RecordReader> reader_;

  std::mutex mu_;
  std::condition_variable work_cv_, done_cv_, space_cv_;
  std::queue<Work> work_q_;
  std::map<uint64_t, Batch> done_;    // reorder buffer keyed by seq
  uint64_t next_out_{0};              // next seq to hand to the consumer
  uint64_t io_seq_{0};
  uint64_t epoch_{0};
  bool io_done_{false};
  int outstanding_{0};                // batches in flight (work_q_ + decoding + done_)
  std::atomic<bool> stop_{false};
  std::string error_;

  std::thread io_thread_;
  std::vector<std::thread> workers_;
};

}  // namespace mxtpu

#endif  // MXTPU_PIPELINE_H_
