// C ABI over the native runtime (reference: src/c_api/ + c_api_error.cc —
// every function returns 0/-1 with a thread-local error string, so any
// language can bind via its FFI; Python binds with ctypes in
// mxnet_tpu/_native.py).
#include <cstring>
#include <string>

#include "../include/mxtpu/c_api.h"  // compiler-checked ABI declarations
#include "common.h"
#include "engine.h"
#include "pipeline.h"
#include "recordio.h"

namespace mxtpu {
static thread_local std::string g_last_error;
void SetLastError(const std::string& msg) { g_last_error = msg; }
const char* GetLastError() { return g_last_error.c_str(); }
}  // namespace mxtpu

using mxtpu::Engine;
using mxtpu::FnProperty;
using mxtpu::Pipeline;
using mxtpu::PipelineConfig;
using mxtpu::RecordReader;
using mxtpu::RecordWriter;

MXTPU_EXPORT const char* MXTPUGetLastError() { return mxtpu::GetLastError(); }

// ---------------------------------------------------------------- engine --
// Op body: runs on a worker thread; return !=0 to mark the op failed.
typedef int (*EngineOpFn)(void* ctx, uint64_t op_id);

MXTPU_EXPORT int MXTPUEngineCreate(int n_workers, int io_workers, void** out) {
  MXTPU_API_BEGIN();
  *out = new Engine(n_workers, io_workers);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineFree(void* h) {
  MXTPU_API_BEGIN();
  delete static_cast<Engine*>(h);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineNewVar(void* h, uint64_t* out) {
  MXTPU_API_BEGIN();
  *out = static_cast<Engine*>(h)->NewVariable();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineDelVar(void* h, uint64_t var) {
  MXTPU_API_BEGIN();
  static_cast<Engine*>(h)->DeleteVariable(var);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEnginePush(void* h, EngineOpFn fn, void* ctx,
                                 const uint64_t* cvars, int ncv,
                                 const uint64_t* mvars, int nmv, int prop,
                                 const char* name, uint64_t* out_op_id) {
  MXTPU_API_BEGIN();
  std::vector<uint64_t> cv(cvars, cvars + ncv), mv(mvars, mvars + nmv);
  std::string nm = name ? name : "";
  uint64_t id = static_cast<Engine*>(h)->PushAsync(
      [fn, ctx, nm](Engine*, uint64_t op_id) {
        if (fn(ctx, op_id) != 0)
          throw std::runtime_error("engine op '" + nm + "' failed");
      },
      cv, mv, static_cast<FnProperty>(prop), nm);
  if (out_op_id) *out_op_id = id;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineOnComplete(void* h, uint64_t op_id) {
  MXTPU_API_BEGIN();
  static_cast<Engine*>(h)->OnComplete(op_id);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineOnCompleteError(void* h, uint64_t op_id,
                                            const char* msg) {
  MXTPU_API_BEGIN();
  static_cast<Engine*>(h)->OnCompleteError(op_id, msg ? msg : "error");
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineWaitForVar(void* h, uint64_t var) {
  MXTPU_API_BEGIN();
  static_cast<Engine*>(h)->WaitForVar(var);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineWaitAll(void* h) {
  MXTPU_API_BEGIN();
  static_cast<Engine*>(h)->WaitForAll();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUEngineNumPending(void* h, int64_t* out) {
  MXTPU_API_BEGIN();
  *out = static_cast<Engine*>(h)->num_pending();
  MXTPU_API_END();
}

// -------------------------------------------------------------- recordio --
MXTPU_EXPORT int MXTPURecordReaderCreate(const char* path, uint64_t chunk,
                                         int part, int nparts, void** out) {
  MXTPU_API_BEGIN();
  *out = new RecordReader(path, chunk, part, nparts);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordReaderNext(void* h, const uint8_t** data,
                                       uint32_t* size) {
  MXTPU_API_BEGIN();
  if (!static_cast<RecordReader*>(h)->NextRecord(data, size)) {
    *data = nullptr;
    *size = 0;
  }
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordReaderSeek(void* h, uint64_t pos) {
  MXTPU_API_BEGIN();
  static_cast<RecordReader*>(h)->Seek(pos);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordReaderTell(void* h, uint64_t* pos) {
  MXTPU_API_BEGIN();
  *pos = static_cast<RecordReader*>(h)->Tell();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordReaderReset(void* h) {
  MXTPU_API_BEGIN();
  static_cast<RecordReader*>(h)->Reset();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordReaderFree(void* h) {
  MXTPU_API_BEGIN();
  delete static_cast<RecordReader*>(h);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordWriterCreate(const char* path, void** out) {
  MXTPU_API_BEGIN();
  *out = new RecordWriter(path);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordWriterWrite(void* h, const uint8_t* data,
                                        uint32_t size, uint64_t* out_pos) {
  MXTPU_API_BEGIN();
  uint64_t pos = static_cast<RecordWriter*>(h)->Write(data, size);
  if (out_pos) *out_pos = pos;
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordWriterTell(void* h, uint64_t* pos) {
  MXTPU_API_BEGIN();
  *pos = static_cast<RecordWriter*>(h)->Tell();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPURecordWriterFree(void* h) {
  MXTPU_API_BEGIN();
  delete static_cast<RecordWriter*>(h);
  MXTPU_API_END();
}

// -------------------------------------------------------------- pipeline --
MXTPU_EXPORT int MXTPUPipelineCreate(
    const char* path, uint64_t chunk_bytes, int part_index, int num_parts,
    int batch_size, uint64_t sample_bytes, int label_width, int shuffle,
    uint64_t seed, int num_workers, int queue_depth, int last_batch_keep,
    mxtpu::DecodeFn decode, void* decode_ctx, void** out) {
  MXTPU_API_BEGIN();
  PipelineConfig cfg;
  cfg.path = path;
  cfg.chunk_bytes = chunk_bytes;
  cfg.part_index = part_index;
  cfg.num_parts = num_parts;
  cfg.batch_size = batch_size;
  cfg.sample_bytes = sample_bytes;
  cfg.label_width = label_width;
  cfg.shuffle = shuffle;
  cfg.seed = seed;
  cfg.num_workers = num_workers;
  cfg.queue_depth = queue_depth;
  cfg.last_batch_keep = last_batch_keep;
  cfg.decode = decode;
  cfg.decode_ctx = decode_ctx;
  *out = new Pipeline(cfg);
  MXTPU_API_END();
}

// Extended create: built-in JPEG decode+augment in the worker pool
// (img_* / rand_* / mean describe the python _augment chain).
MXTPU_EXPORT int MXTPUPipelineCreateJpeg(
    const char* path, uint64_t chunk_bytes, int part_index, int num_parts,
    int batch_size, uint64_t sample_bytes, int label_width, int shuffle,
    uint64_t seed, int num_workers, int queue_depth, int last_batch_keep,
    int img_h, int img_w, int img_c, int rand_crop, int rand_mirror,
    float mean_r, float mean_g, float mean_b, mxtpu::DecodeFn fallback,
    void* fallback_ctx, void** out) {
  MXTPU_API_BEGIN();
  PipelineConfig cfg;
  cfg.path = path;
  cfg.chunk_bytes = chunk_bytes;
  cfg.part_index = part_index;
  cfg.num_parts = num_parts;
  cfg.batch_size = batch_size;
  cfg.sample_bytes = sample_bytes;
  cfg.label_width = label_width;
  cfg.shuffle = shuffle;
  cfg.seed = seed;
  cfg.num_workers = num_workers;
  cfg.queue_depth = queue_depth;
  cfg.last_batch_keep = last_batch_keep;
  cfg.builtin_jpeg = 1;
  cfg.img_h = img_h;
  cfg.img_w = img_w;
  cfg.img_c = img_c;
  cfg.rand_crop = rand_crop;
  cfg.rand_mirror = rand_mirror;
  cfg.mean[0] = mean_r;
  cfg.mean[1] = mean_g;
  cfg.mean[2] = mean_b;
  cfg.jpeg_fallback = fallback;
  cfg.decode_ctx = fallback_ctx;
  *out = new Pipeline(cfg);
  MXTPU_API_END();
}

// 1 when libmxtpu was built against libjpeg (the builtin JPEG worker
// path is available), else 0.
MXTPU_EXPORT int MXTPUPipelineHasJpeg() {
#ifdef MXTPU_USE_LIBJPEG
  return 1;
#else
  return 0;
#endif
}

// count is set to -1 at end of epoch.
MXTPU_EXPORT int MXTPUPipelineNext(void* h, uint8_t** data, float** label,
                                   int* count) {
  MXTPU_API_BEGIN();
  mxtpu::Batch b;
  if (static_cast<Pipeline*>(h)->Next(&b)) {
    *data = b.data;
    *label = b.label;
    *count = b.count;
  } else {
    *data = nullptr;
    *label = nullptr;
    *count = -1;
  }
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPipelineRelease(void* h, uint8_t* data, float* label) {
  MXTPU_API_BEGIN();
  mxtpu::Batch b;
  b.data = data;
  b.label = label;
  static_cast<Pipeline*>(h)->Release(b);
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPipelineReset(void* h) {
  MXTPU_API_BEGIN();
  static_cast<Pipeline*>(h)->Reset();
  MXTPU_API_END();
}

MXTPU_EXPORT int MXTPUPipelineFree(void* h) {
  MXTPU_API_BEGIN();
  delete static_cast<Pipeline*>(h);
  MXTPU_API_END();
}
