// Embedded-CPython bridge shared by the C ABIs that drive the jax runtime
// from plain C (predict.cc, c_api_tensor.cc).
//
// On TPU the tensor runtime IS jax/XLA/PJRT, so instead of maintaining a
// second compute engine the C ABI hosts a CPython interpreter (dlopen'd
// lazily, never a link-time dependency) and calls into a marshalling
// module inside mxnet_tpu.  All data crosses the boundary as integer
// addresses formatted into interpreter source — no CPython API types
// appear in libmxtpu, so it builds with no Python headers.
// Reference analog: src/c_api/*.cc calling into the C++ runtime directly.
#ifndef MXTPU_EMBED_H_
#define MXTPU_EMBED_H_

#include <string>

namespace mxtpu {

// Comma-joined integer argument list for EmbedCall.  Pointers and
// integers only — wider types are passed by address.
class EmbedArgs {
 public:
  EmbedArgs& p(const void* ptr);       // pointer → integer literal
  EmbedArgs& u(unsigned long long v);  // unsigned integer literal
  EmbedArgs& i(long long v);           // signed integer literal
  const std::string& str() const { return s_; }

 private:
  void Sep();
  std::string s_;
};

// Run mxnet_tpu.<module>.<fn>(<args>, &status, errbuf, errcap) inside the
// embedded interpreter (GIL taken around the call).  The Python callee is
// no-raise by contract: it reports failure through the (status, errbuf)
// out-parameters, which this function surfaces as std::runtime_error —
// caught by MXTPU_API_END into the thread-local error string.
void EmbedCall(const char* module, const char* fn, const std::string& args);

}  // namespace mxtpu

#endif  // MXTPU_EMBED_H_
