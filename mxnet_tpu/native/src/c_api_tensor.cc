// Tensor-runtime C ABI (NDArray / op / autograd / Symbol / Executor /
// CachedOp / DataIter / KVStore / profiler groups of mxtpu/c_api.h).
//
// Reference: src/c_api/{c_api.cc,c_api_symbolic.cc,c_api_executor.cc,
// c_api_ndarray.cc,c_api_profile.cc} — there the C layer calls the C++
// runtime directly.  Here the tensor runtime is jax/XLA reached through
// the embedded interpreter (embed.h): each extern formats its raw
// argument addresses into a call on mxnet_tpu._c_embed, which performs
// ALL marshalling (reading C arrays, writing out-params, pinning
// returned storage) with ctypes.  This file stays logic-free by design:
// one semantic implementation lives in Python, the ABI is a transport.
#include <string>

#include "../include/mxtpu/c_api.h"
#include "common.h"
#include "embed.h"

using mxtpu::EmbedArgs;

namespace {
void TCall(const char* fn, const EmbedArgs& a) {
  mxtpu::EmbedCall("_c_embed", fn, a.str());
}
}  // namespace

#define MXTPU_TCALL(fn, body)    \
  MXTPU_API_BEGIN();             \
  EmbedArgs a;                   \
  body;                          \
  TCall(fn, a);                  \
  MXTPU_API_END()

/* ------------------------------------------------------------------ base */

int MXTPUGetVersion(int* out) {
  MXTPU_TCALL("get_version", a.p(out));
}

int MXTPURandomSeed(int seed) {
  MXTPU_TCALL("random_seed", a.i(seed));
}

int MXTPURandomSeedContext(int seed, int dev_type, int dev_id) {
  MXTPU_TCALL("random_seed_context", a.i(seed).i(dev_type).i(dev_id));
}

int MXTPUNotifyShutdown(void) {
  MXTPU_TCALL("notify_shutdown", (void)a);
}

int MXTPUSetNumOMPThreads(int nthreads) {
  MXTPU_TCALL("set_num_omp_threads", a.i(nthreads));
}

int MXTPUEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  MXTPU_TCALL("engine_set_bulk_size", a.i(bulk_size).p(prev_bulk_size));
}

int MXTPUGetDeviceCount(int* out) {
  MXTPU_TCALL("get_device_count", a.p(out));
}

int MXTPUGetDeviceMemoryInformation(int dev_id, uint64_t* free_mem,
                                    uint64_t* total_mem) {
  MXTPU_TCALL("get_device_memory_information",
              a.i(dev_id).p(free_mem).p(total_mem));
}

int MXTPULibInfoFeatures(const char*** out_names, const int** out_enabled,
                         uint64_t* out_size) {
  MXTPU_TCALL("lib_info_features", a.p(out_names).p(out_enabled).p(out_size));
}

/* --------------------------------------------------------------- ndarray */

int MXTPUNDArrayCreateNone(MXTPUHandle* out) {
  MXTPU_TCALL("nd_create_none", a.p(out));
}

int MXTPUNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                       int dev_id, int delay_alloc, MXTPUHandle* out) {
  MXTPU_TCALL("nd_create", a.p(shape).u(ndim).i(dev_type).i(dev_id)
                               .i(delay_alloc).i(0).p(out));
}

int MXTPUNDArrayCreateEx(const uint32_t* shape, uint32_t ndim, int dev_type,
                         int dev_id, int delay_alloc, int dtype,
                         MXTPUHandle* out) {
  MXTPU_TCALL("nd_create", a.p(shape).u(ndim).i(dev_type).i(dev_id)
                               .i(delay_alloc).i(dtype).p(out));
}

int MXTPUNDArrayFree(MXTPUHandle handle) {
  MXTPU_TCALL("nd_free", a.u(handle));
}

int MXTPUNDArrayGetShape(MXTPUHandle handle, uint32_t* out_ndim,
                         const uint32_t** out_pdata) {
  MXTPU_TCALL("nd_get_shape", a.u(handle).p(out_ndim).p(out_pdata));
}

int MXTPUNDArrayGetDType(MXTPUHandle handle, int* out) {
  MXTPU_TCALL("nd_get_dtype", a.u(handle).p(out));
}

int MXTPUNDArrayGetContext(MXTPUHandle handle, int* out_dev_type,
                           int* out_dev_id) {
  MXTPU_TCALL("nd_get_context", a.u(handle).p(out_dev_type).p(out_dev_id));
}

int MXTPUNDArrayGetData(MXTPUHandle handle, void** out_pdata) {
  MXTPU_TCALL("nd_get_data", a.u(handle).p(out_pdata));
}

int MXTPUNDArraySyncCopyFromCPU(MXTPUHandle handle, const void* data,
                                uint64_t size) {
  MXTPU_TCALL("nd_sync_copy_from_cpu", a.u(handle).p(data).u(size));
}

int MXTPUNDArraySyncCopyToCPU(MXTPUHandle handle, void* data, uint64_t size) {
  MXTPU_TCALL("nd_sync_copy_to_cpu", a.u(handle).p(data).u(size));
}

int MXTPUNDArraySyncCopyFromNDArray(MXTPUHandle dst, MXTPUHandle src, int i) {
  MXTPU_TCALL("nd_sync_copy_from_ndarray", a.u(dst).u(src).i(i));
}

int MXTPUNDArraySlice(MXTPUHandle handle, uint32_t slice_begin,
                      uint32_t slice_end, MXTPUHandle* out) {
  MXTPU_TCALL("nd_slice", a.u(handle).u(slice_begin).u(slice_end).p(out));
}

int MXTPUNDArrayAt(MXTPUHandle handle, uint32_t idx, MXTPUHandle* out) {
  MXTPU_TCALL("nd_at", a.u(handle).u(idx).p(out));
}

int MXTPUNDArrayReshape(MXTPUHandle handle, int ndim, const int* dims,
                        MXTPUHandle* out) {
  MXTPU_TCALL("nd_reshape", a.u(handle).i(ndim).p(dims).i(0).p(out));
}

int MXTPUNDArrayReshape64(MXTPUHandle handle, int ndim, const int64_t* dims,
                          int reverse, MXTPUHandle* out) {
  MXTPU_TCALL("nd_reshape64", a.u(handle).i(ndim).p(dims).i(reverse).p(out));
}

int MXTPUNDArrayDetach(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("nd_detach", a.u(handle).p(out));
}

int MXTPUNDArraySetGradState(MXTPUHandle handle, int state) {
  MXTPU_TCALL("nd_set_grad_state", a.u(handle).i(state));
}

int MXTPUNDArrayGetGradState(MXTPUHandle handle, int* out) {
  MXTPU_TCALL("nd_get_grad_state", a.u(handle).p(out));
}

int MXTPUNDArrayGetGrad(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("nd_get_grad", a.u(handle).p(out));
}

int MXTPUNDArrayWaitToRead(MXTPUHandle handle) {
  MXTPU_TCALL("nd_wait_to_read", a.u(handle));
}

int MXTPUNDArrayWaitToWrite(MXTPUHandle handle) {
  MXTPU_TCALL("nd_wait_to_write", a.u(handle));
}

int MXTPUNDArrayWaitAll(void) {
  MXTPU_TCALL("nd_wait_all", (void)a);
}

int MXTPUNDArraySave(const char* fname, uint32_t num_args,
                     const MXTPUHandle* args, const char** keys) {
  MXTPU_TCALL("nd_save", a.p(fname).u(num_args).p(args).p(keys));
}

int MXTPUNDArrayLoad(const char* fname, uint32_t* out_size,
                     MXTPUHandle** out_arr, uint32_t* out_name_size,
                     const char*** out_names) {
  MXTPU_TCALL("nd_load",
              a.p(fname).p(out_size).p(out_arr).p(out_name_size).p(out_names));
}

int MXTPUNDArrayLoadFromBuffer(const void* ndarray_buffer, uint64_t size,
                               uint32_t* out_size, MXTPUHandle** out_arr,
                               uint32_t* out_name_size,
                               const char*** out_names) {
  MXTPU_TCALL("nd_load_from_buffer", a.p(ndarray_buffer).u(size).p(out_size)
                                         .p(out_arr).p(out_name_size)
                                         .p(out_names));
}

int MXTPUNDArraySaveRawBytes(MXTPUHandle handle, uint64_t* out_size,
                             const char** out_buf) {
  MXTPU_TCALL("nd_save_raw_bytes", a.u(handle).p(out_size).p(out_buf));
}

int MXTPUNDArrayLoadFromRawBytes(const void* buf, uint64_t size,
                                 MXTPUHandle* out) {
  MXTPU_TCALL("nd_load_from_raw_bytes", a.p(buf).u(size).p(out));
}

int MXTPUNDArrayGetStorageType(MXTPUHandle handle, int* out) {
  MXTPU_TCALL("nd_get_storage_type", a.u(handle).p(out));
}

int MXTPUNDArrayCreateSparseEx(int storage_type, const uint32_t* shape,
                               uint32_t ndim, int dev_type, int dev_id,
                               int delay_alloc, int dtype, uint32_t num_aux,
                               const int* aux_type, const uint32_t* aux_ndims,
                               const uint32_t* aux_shape, MXTPUHandle* out) {
  MXTPU_TCALL("nd_create_sparse",
              a.i(storage_type).p(shape).u(ndim).i(dev_type).i(dev_id)
                  .i(delay_alloc).i(dtype).u(num_aux).p(aux_type)
                  .p(aux_ndims).p(aux_shape).p(out));
}

int MXTPUNDArrayGetAuxType(MXTPUHandle handle, uint32_t i, int* out) {
  MXTPU_TCALL("nd_get_aux_type", a.u(handle).u(i).p(out));
}

int MXTPUNDArrayGetAuxNDArray(MXTPUHandle handle, uint32_t i,
                              MXTPUHandle* out) {
  MXTPU_TCALL("nd_get_aux_ndarray", a.u(handle).u(i).p(out));
}

int MXTPUNDArrayGetDataNDArray(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("nd_get_data_ndarray", a.u(handle).p(out));
}

int MXTPUNDArraySyncCheckFormat(MXTPUHandle handle, int full_check) {
  MXTPU_TCALL("nd_sync_check_format", a.u(handle).i(full_check));
}

int MXTPUNDArrayToDLPack(MXTPUHandle handle, void** out_dlmanaged) {
  MXTPU_TCALL("nd_to_dlpack", a.u(handle).p(out_dlmanaged));
}

int MXTPUNDArrayFromDLPack(void* dlmanaged, MXTPUHandle* out) {
  MXTPU_TCALL("nd_from_dlpack", a.p(dlmanaged).p(out));
}

int MXTPUNDArrayCallDLPackDeleter(void* dlmanaged) {
  MXTPU_TCALL("nd_call_dlpack_deleter", a.p(dlmanaged));
}

int MXTPUNDArrayGetSharedMemHandle(MXTPUHandle handle, int* shared_pid,
                                   int* shared_id) {
  MXTPU_TCALL("nd_get_shared_mem_handle",
              a.u(handle).p(shared_pid).p(shared_id));
}

int MXTPUNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                    const uint32_t* shape, uint32_t ndim,
                                    int dtype, MXTPUHandle* out) {
  MXTPU_TCALL("nd_create_from_shared_mem",
              a.i(shared_pid).i(shared_id).p(shape).u(ndim).i(dtype).p(out));
}

/* ------------------------------------------------- ops & imperative call */

int MXTPUListAllOpNames(uint32_t* out_size, const char*** out_array) {
  MXTPU_TCALL("list_all_op_names", a.p(out_size).p(out_array));
}

int MXTPUGetOpHandle(const char* op_name, MXTPUHandle* out) {
  MXTPU_TCALL("get_op_handle", a.p(op_name).p(out));
}

int MXTPUGetOpInfo(MXTPUHandle op, const char** name,
                   const char** description, uint32_t* num_args,
                   const char*** arg_names, const char*** arg_types,
                   const char*** arg_descriptions, const char** return_type) {
  MXTPU_TCALL("get_op_info", a.u(op).p(name).p(description).p(num_args)
                                 .p(arg_names).p(arg_types)
                                 .p(arg_descriptions).p(return_type));
}

int MXTPUImperativeInvoke(MXTPUHandle op, int num_inputs,
                          const MXTPUHandle* inputs, int* num_outputs,
                          MXTPUHandle** outputs, int num_params,
                          const char** param_keys, const char** param_vals) {
  MXTPU_TCALL("imperative_invoke",
              a.u(op).i(num_inputs).p(inputs).p(num_outputs).p(outputs)
                  .i(num_params).p(param_keys).p(param_vals));
}

int MXTPUListFunctions(uint32_t* out_size, MXTPUHandle** out_array) {
  MXTPU_TCALL("list_functions", a.p(out_size).p(out_array));
}

int MXTPUGetFunction(const char* name, MXTPUHandle* out) {
  MXTPU_TCALL("get_op_handle", a.p(name).p(out));
}

int MXTPUFuncGetInfo(MXTPUHandle fun, const char** name,
                     const char** description, uint32_t* num_args,
                     const char*** arg_names, const char*** arg_types,
                     const char*** arg_descriptions,
                     const char** return_type) {
  MXTPU_TCALL("get_op_info", a.u(fun).p(name).p(description).p(num_args)
                                 .p(arg_names).p(arg_types)
                                 .p(arg_descriptions).p(return_type));
}

int MXTPUFuncInvoke(MXTPUHandle fun, const MXTPUHandle* use_vars,
                    const float* scalar_args, const MXTPUHandle* mutate_vars,
                    int num_use, int num_scalar, int num_mutate) {
  MXTPU_TCALL("func_invoke", a.u(fun).p(use_vars).p(scalar_args)
                                 .p(mutate_vars).i(num_use).i(num_scalar)
                                 .i(num_mutate).i(0).u(0).u(0));
}

int MXTPUFuncInvokeEx(MXTPUHandle fun, const MXTPUHandle* use_vars,
                      const float* scalar_args, const MXTPUHandle* mutate_vars,
                      int num_use, int num_scalar, int num_mutate,
                      int num_params, const char** param_keys,
                      const char** param_vals) {
  MXTPU_TCALL("func_invoke", a.u(fun).p(use_vars).p(scalar_args)
                                 .p(mutate_vars).i(num_use).i(num_scalar)
                                 .i(num_mutate).i(num_params).p(param_keys)
                                 .p(param_vals));
}

/* -------------------------------------------------------------- autograd */

int MXTPUAutogradSetIsRecording(int is_recording, int* prev) {
  MXTPU_TCALL("autograd_set_is_recording", a.i(is_recording).p(prev));
}

int MXTPUAutogradSetIsTraining(int is_training, int* prev) {
  MXTPU_TCALL("autograd_set_is_training", a.i(is_training).p(prev));
}

int MXTPUAutogradIsRecording(int* curr) {
  MXTPU_TCALL("autograd_is_recording", a.p(curr));
}

int MXTPUAutogradIsTraining(int* curr) {
  MXTPU_TCALL("autograd_is_training", a.p(curr));
}

int MXTPUAutogradMarkVariables(uint32_t num_var,
                               const MXTPUHandle* var_handles,
                               const uint32_t* reqs_array,
                               const MXTPUHandle* grad_handles) {
  MXTPU_TCALL("autograd_mark_variables",
              a.u(num_var).p(var_handles).p(reqs_array).p(grad_handles));
}

int MXTPUAutogradBackward(uint32_t num_output,
                          const MXTPUHandle* output_handles,
                          const MXTPUHandle* ograd_handles, int retain_graph) {
  MXTPU_TCALL("autograd_backward",
              a.u(num_output).p(output_handles).p(ograd_handles).u(0).u(0)
                  .i(retain_graph).i(0).i(1).u(0).u(0));
}

int MXTPUAutogradBackwardEx(uint32_t num_output,
                            const MXTPUHandle* output_handles,
                            const MXTPUHandle* ograd_handles,
                            uint32_t num_variables,
                            const MXTPUHandle* var_handles, int retain_graph,
                            int create_graph, int is_train,
                            MXTPUHandle** grad_handles,
                            const int** grad_stypes) {
  MXTPU_TCALL("autograd_backward",
              a.u(num_output).p(output_handles).p(ograd_handles)
                  .u(num_variables).p(var_handles).i(retain_graph)
                  .i(create_graph).i(is_train).p(grad_handles)
                  .p(grad_stypes));
}

int MXTPUAutogradComputeGradient(uint32_t num_output,
                                 const MXTPUHandle* output_handles) {
  MXTPU_TCALL("autograd_backward",
              a.u(num_output).p(output_handles).u(0).u(0).u(0).i(0).i(0).i(1)
                  .u(0).u(0));
}

int MXTPUAutogradGetSymbol(MXTPUHandle ndhandle, MXTPUHandle* out) {
  MXTPU_TCALL("autograd_get_symbol", a.u(ndhandle).p(out));
}

/* ---------------------------------------------------------------- symbol */

int MXTPUSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                        MXTPUHandle** out_array) {
  MXTPU_TCALL("list_functions", a.p(out_size).p(out_array));
}

int MXTPUSymbolGetAtomicSymbolName(MXTPUHandle creator, const char** name) {
  MXTPU_TCALL("sym_get_atomic_symbol_name", a.u(creator).p(name));
}

int MXTPUSymbolGetAtomicSymbolInfo(MXTPUHandle creator, const char** name,
                                   const char** description,
                                   uint32_t* num_args,
                                   const char*** arg_names,
                                   const char*** arg_types,
                                   const char*** arg_descriptions,
                                   const char** key_var_num_args,
                                   const char** return_type) {
  MXTPU_TCALL("sym_get_atomic_symbol_info",
              a.u(creator).p(name).p(description).p(num_args).p(arg_names)
                  .p(arg_types).p(arg_descriptions).p(key_var_num_args)
                  .p(return_type));
}

int MXTPUSymbolCreateAtomicSymbol(MXTPUHandle creator, uint32_t num_param,
                                  const char** keys, const char** vals,
                                  MXTPUHandle* out) {
  MXTPU_TCALL("sym_create_atomic_symbol",
              a.u(creator).u(num_param).p(keys).p(vals).p(out));
}

int MXTPUSymbolCreateVariable(const char* name, MXTPUHandle* out) {
  MXTPU_TCALL("sym_create_variable", a.p(name).p(out));
}

int MXTPUSymbolCreateGroup(uint32_t num_symbols, const MXTPUHandle* symbols,
                           MXTPUHandle* out) {
  MXTPU_TCALL("sym_create_group", a.u(num_symbols).p(symbols).p(out));
}

int MXTPUSymbolCreateFromFile(const char* fname, MXTPUHandle* out) {
  MXTPU_TCALL("sym_create_from_file", a.p(fname).p(out));
}

int MXTPUSymbolCreateFromJSON(const char* json, MXTPUHandle* out) {
  MXTPU_TCALL("sym_create_from_json", a.p(json).p(out));
}

int MXTPUSymbolSaveToFile(MXTPUHandle symbol, const char* fname) {
  MXTPU_TCALL("sym_save_to_file", a.u(symbol).p(fname));
}

int MXTPUSymbolSaveToJSON(MXTPUHandle symbol, const char** out_json) {
  MXTPU_TCALL("sym_save_to_json", a.u(symbol).p(out_json));
}

int MXTPUSymbolFree(MXTPUHandle symbol) {
  MXTPU_TCALL("sym_free", a.u(symbol));
}

int MXTPUSymbolCopy(MXTPUHandle symbol, MXTPUHandle* out) {
  MXTPU_TCALL("sym_copy", a.u(symbol).p(out));
}

int MXTPUSymbolPrint(MXTPUHandle symbol, const char** out_str) {
  MXTPU_TCALL("sym_print", a.u(symbol).p(out_str));
}

int MXTPUSymbolGetName(MXTPUHandle symbol, const char** out, int* success) {
  MXTPU_TCALL("sym_get_name", a.u(symbol).p(out).p(success));
}

int MXTPUSymbolGetAttr(MXTPUHandle symbol, const char* key, const char** out,
                       int* success) {
  MXTPU_TCALL("sym_get_attr", a.u(symbol).p(key).p(out).p(success));
}

int MXTPUSymbolSetAttr(MXTPUHandle symbol, const char* key,
                       const char* value) {
  MXTPU_TCALL("sym_set_attr", a.u(symbol).p(key).p(value));
}

int MXTPUSymbolListAttr(MXTPUHandle symbol, uint32_t* out_size,
                        const char*** out) {
  MXTPU_TCALL("sym_list_attr", a.u(symbol).i(0).p(out_size).p(out));
}

int MXTPUSymbolListAttrShallow(MXTPUHandle symbol, uint32_t* out_size,
                               const char*** out) {
  MXTPU_TCALL("sym_list_attr", a.u(symbol).i(1).p(out_size).p(out));
}

int MXTPUSymbolListArguments(MXTPUHandle symbol, uint32_t* out_size,
                             const char*** out_str_array) {
  MXTPU_TCALL("sym_list_arguments", a.u(symbol).p(out_size).p(out_str_array));
}

int MXTPUSymbolListOutputs(MXTPUHandle symbol, uint32_t* out_size,
                           const char*** out_str_array) {
  MXTPU_TCALL("sym_list_outputs", a.u(symbol).p(out_size).p(out_str_array));
}

int MXTPUSymbolListAuxiliaryStates(MXTPUHandle symbol, uint32_t* out_size,
                                   const char*** out_str_array) {
  MXTPU_TCALL("sym_list_auxiliary_states",
              a.u(symbol).p(out_size).p(out_str_array));
}

int MXTPUSymbolGetNumOutputs(MXTPUHandle symbol, uint32_t* output_count) {
  MXTPU_TCALL("sym_get_num_outputs", a.u(symbol).p(output_count));
}

int MXTPUSymbolGetInternals(MXTPUHandle symbol, MXTPUHandle* out) {
  MXTPU_TCALL("sym_get_internals", a.u(symbol).p(out));
}

int MXTPUSymbolGetChildren(MXTPUHandle symbol, MXTPUHandle* out) {
  MXTPU_TCALL("sym_get_children", a.u(symbol).p(out));
}

int MXTPUSymbolGetOutput(MXTPUHandle symbol, uint32_t index,
                         MXTPUHandle* out) {
  MXTPU_TCALL("sym_get_output", a.u(symbol).u(index).p(out));
}

int MXTPUSymbolGetInputSymbols(MXTPUHandle symbol, MXTPUHandle** out_handles,
                               uint32_t* out_size) {
  MXTPU_TCALL("sym_get_input_symbols", a.u(symbol).p(out_handles).p(out_size));
}

int MXTPUSymbolCompose(MXTPUHandle symbol, const char* name,
                       uint32_t num_args, const char** keys,
                       const MXTPUHandle* args) {
  MXTPU_TCALL("sym_compose", a.u(symbol).p(name).u(num_args).p(keys).p(args));
}

int MXTPUSymbolInferShape(MXTPUHandle sym, uint32_t num_args,
                          const char** keys, const uint32_t* arg_ind_ptr,
                          const uint32_t* arg_shape_data,
                          uint32_t* in_shape_size,
                          const uint32_t** in_shape_ndim,
                          const uint32_t*** in_shape_data,
                          uint32_t* out_shape_size,
                          const uint32_t** out_shape_ndim,
                          const uint32_t*** out_shape_data,
                          uint32_t* aux_shape_size,
                          const uint32_t** aux_shape_ndim,
                          const uint32_t*** aux_shape_data, int* complete) {
  MXTPU_TCALL("sym_infer_shape",
              a.u(sym).i(0).u(num_args).p(keys).p(arg_ind_ptr)
                  .p(arg_shape_data).p(in_shape_size).p(in_shape_ndim)
                  .p(in_shape_data).p(out_shape_size).p(out_shape_ndim)
                  .p(out_shape_data).p(aux_shape_size).p(aux_shape_ndim)
                  .p(aux_shape_data).p(complete));
}

int MXTPUSymbolInferShapePartial(
    MXTPUHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete) {
  MXTPU_TCALL("sym_infer_shape",
              a.u(sym).i(1).u(num_args).p(keys).p(arg_ind_ptr)
                  .p(arg_shape_data).p(in_shape_size).p(in_shape_ndim)
                  .p(in_shape_data).p(out_shape_size).p(out_shape_ndim)
                  .p(out_shape_data).p(aux_shape_size).p(aux_shape_ndim)
                  .p(aux_shape_data).p(complete));
}

int MXTPUSymbolInferType(MXTPUHandle sym, uint32_t num_args,
                         const char** keys, const int* arg_type_data,
                         uint32_t* in_type_size, const int** in_type_data,
                         uint32_t* out_type_size, const int** out_type_data,
                         uint32_t* aux_type_size, const int** aux_type_data,
                         int* complete) {
  MXTPU_TCALL("sym_infer_type",
              a.u(sym).u(num_args).p(keys).p(arg_type_data).p(in_type_size)
                  .p(in_type_data).p(out_type_size).p(out_type_data)
                  .p(aux_type_size).p(aux_type_data).p(complete));
}

int MXTPUQuantizeSymbol(MXTPUHandle sym, MXTPUHandle* out,
                        uint32_t num_excluded,
                        const char** excluded_op_names,
                        const char* quantized_dtype) {
  MXTPU_TCALL("quantize_symbol", a.u(sym).p(out).u(num_excluded)
                                     .p(excluded_op_names)
                                     .p(quantized_dtype));
}

int MXTPUSetCalibTableToQuantizedSymbol(MXTPUHandle qsym, uint32_t num_layers,
                                        const char** layer_names,
                                        const float* low_quantiles,
                                        const float* high_quantiles,
                                        MXTPUHandle* out) {
  MXTPU_TCALL("set_calib_table_to_quantized_symbol",
              a.u(qsym).u(num_layers).p(layer_names).p(low_quantiles)
                  .p(high_quantiles).p(out));
}

int MXTPUGenBackendSubgraph(MXTPUHandle sym, const char* backend,
                            MXTPUHandle* out) {
  MXTPU_TCALL("gen_backend_subgraph", a.u(sym).p(backend).p(out));
}

/* -------------------------------------------------------------- executor */

int MXTPUExecutorFree(MXTPUHandle handle) {
  MXTPU_TCALL("exec_free", a.u(handle));
}

int MXTPUExecutorPrint(MXTPUHandle handle, const char** out_str) {
  MXTPU_TCALL("exec_print", a.u(handle).p(out_str));
}

int MXTPUExecutorForward(MXTPUHandle handle, int is_train) {
  MXTPU_TCALL("exec_forward", a.u(handle).i(is_train));
}

int MXTPUExecutorBackward(MXTPUHandle handle, uint32_t len,
                          const MXTPUHandle* head_grads) {
  MXTPU_TCALL("exec_backward", a.u(handle).u(len).p(head_grads).i(1));
}

int MXTPUExecutorBackwardEx(MXTPUHandle handle, uint32_t len,
                            const MXTPUHandle* head_grads, int is_train) {
  MXTPU_TCALL("exec_backward", a.u(handle).u(len).p(head_grads).i(is_train));
}

int MXTPUExecutorOutputs(MXTPUHandle handle, uint32_t* out_size,
                         MXTPUHandle** out) {
  MXTPU_TCALL("exec_outputs", a.u(handle).p(out_size).p(out));
}

int MXTPUExecutorBind(MXTPUHandle symbol_handle, int dev_type, int dev_id,
                      uint32_t len, const MXTPUHandle* in_args,
                      const MXTPUHandle* arg_grad_store,
                      const uint32_t* grad_req_type, uint32_t aux_len,
                      const MXTPUHandle* aux_states, MXTPUHandle* out) {
  MXTPU_TCALL("exec_bind",
              a.u(symbol_handle).i(dev_type).i(dev_id).u(len).p(in_args)
                  .p(arg_grad_store).p(grad_req_type).u(aux_len)
                  .p(aux_states).u(0).p(out));
}

int MXTPUExecutorBindX(MXTPUHandle symbol_handle, int dev_type, int dev_id,
                       uint32_t num_map_keys, const char** map_keys,
                       const int* map_dev_types, const int* map_dev_ids,
                       uint32_t len, const MXTPUHandle* in_args,
                       const MXTPUHandle* arg_grad_store,
                       const uint32_t* grad_req_type, uint32_t aux_len,
                       const MXTPUHandle* aux_states, MXTPUHandle* out) {
  (void)num_map_keys; (void)map_keys; (void)map_dev_types; (void)map_dev_ids;
  return MXTPUExecutorBind(symbol_handle, dev_type, dev_id, len, in_args,
                           arg_grad_store, grad_req_type, aux_len, aux_states,
                           out);
}

int MXTPUExecutorBindEX(MXTPUHandle symbol_handle, int dev_type, int dev_id,
                        uint32_t num_map_keys, const char** map_keys,
                        const int* map_dev_types, const int* map_dev_ids,
                        uint32_t len, const MXTPUHandle* in_args,
                        const MXTPUHandle* arg_grad_store,
                        const uint32_t* grad_req_type, uint32_t aux_len,
                        const MXTPUHandle* aux_states, MXTPUHandle shared_exec,
                        MXTPUHandle* out) {
  (void)num_map_keys; (void)map_keys; (void)map_dev_types; (void)map_dev_ids;
  MXTPU_TCALL("exec_bind",
              a.u(symbol_handle).i(dev_type).i(dev_id).u(len).p(in_args)
                  .p(arg_grad_store).p(grad_req_type).u(aux_len)
                  .p(aux_states).u(shared_exec).p(out));
}

int MXTPUExecutorSimpleBind(
    MXTPUHandle symbol_handle, int dev_type, int dev_id,
    uint32_t num_g2c_keys, const char** g2c_keys, const int* g2c_dev_types,
    const int* g2c_dev_ids, uint32_t provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types, uint32_t num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const uint32_t* provided_arg_shape_data,
    const uint32_t* provided_arg_shape_idx, uint32_t num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    uint32_t num_provided_arg_stypes, const char** provided_arg_stype_names,
    const int* provided_arg_stypes, uint32_t num_shared_arg_names,
    const char** shared_arg_name_list, int* shared_buffer_len,
    const char** shared_buffer_name_list,
    const MXTPUHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    MXTPUHandle** updated_shared_buffer_handle_list, uint32_t* num_in_args,
    MXTPUHandle** in_args, MXTPUHandle** arg_grads, uint32_t* num_aux_states,
    MXTPUHandle** aux_states, MXTPUHandle shared_exec_handle,
    MXTPUHandle* out) {
  (void)num_g2c_keys; (void)g2c_keys; (void)g2c_dev_types; (void)g2c_dev_ids;
  MXTPU_TCALL("exec_simple_bind",
              a.u(symbol_handle).i(dev_type).i(dev_id)
                  .u(provided_grad_req_list_len).p(provided_grad_req_names)
                  .p(provided_grad_req_types).u(num_provided_arg_shapes)
                  .p(provided_arg_shape_names).p(provided_arg_shape_data)
                  .p(provided_arg_shape_idx).u(num_provided_arg_dtypes)
                  .p(provided_arg_dtype_names).p(provided_arg_dtypes)
                  .u(num_provided_arg_stypes).p(provided_arg_stype_names)
                  .p(provided_arg_stypes).u(num_shared_arg_names)
                  .p(shared_arg_name_list).p(shared_buffer_len)
                  .p(shared_buffer_name_list).p(shared_buffer_handle_list)
                  .p(updated_shared_buffer_name_list)
                  .p(updated_shared_buffer_handle_list).p(num_in_args)
                  .p(in_args).p(arg_grads).p(num_aux_states).p(aux_states)
                  .u(shared_exec_handle).p(out));
}

int MXTPUExecutorReshape(int partial_shaping, int allow_up_sizing,
                         int dev_type, int dev_id, uint32_t num_map_keys,
                         const char** map_keys, const int* map_dev_types,
                         const int* map_dev_ids,
                         uint32_t num_provided_arg_shapes,
                         const char** provided_arg_shape_names,
                         const uint32_t* provided_arg_shape_data,
                         const uint32_t* provided_arg_shape_idx,
                         uint32_t* num_in_args, MXTPUHandle** in_args,
                         MXTPUHandle** arg_grads, uint32_t* num_aux_states,
                         MXTPUHandle** aux_states, MXTPUHandle shared_exec,
                         MXTPUHandle* out) {
  (void)num_map_keys; (void)map_keys; (void)map_dev_types; (void)map_dev_ids;
  MXTPU_TCALL("exec_reshape",
              a.i(partial_shaping).i(allow_up_sizing).i(dev_type).i(dev_id)
                  .u(num_provided_arg_shapes).p(provided_arg_shape_names)
                  .p(provided_arg_shape_data).p(provided_arg_shape_idx)
                  .p(num_in_args).p(in_args).p(arg_grads).p(num_aux_states)
                  .p(aux_states).u(shared_exec).p(out));
}

int MXTPUExecutorGetOptimizedSymbol(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("exec_get_optimized_symbol", a.u(handle).p(out));
}

int MXTPUExecutorSetMonitorCallback(MXTPUHandle handle,
                                    MXTPUExecutorMonitorCallback cb,
                                    void* callback_ctx) {
  MXTPU_TCALL("exec_set_monitor_callback",
              a.u(handle).p((void*)cb).p(callback_ctx).i(0));
}

int MXTPUExecutorSetMonitorCallbackEX(MXTPUHandle handle,
                                      MXTPUExecutorMonitorCallback cb,
                                      void* callback_ctx, int monitor_all) {
  MXTPU_TCALL("exec_set_monitor_callback",
              a.u(handle).p((void*)cb).p(callback_ctx).i(monitor_all));
}

/* ------------------------------------------------------------- cached op */

int MXTPUCreateCachedOp(MXTPUHandle sym_handle, MXTPUHandle* out) {
  MXTPU_TCALL("create_cached_op", a.u(sym_handle).i(0).u(0).u(0).p(out));
}

int MXTPUCreateCachedOpEx(MXTPUHandle sym_handle, int num_flags,
                          const char** keys, const char** vals,
                          MXTPUHandle* out) {
  MXTPU_TCALL("create_cached_op",
              a.u(sym_handle).i(num_flags).p(keys).p(vals).p(out));
}

int MXTPUFreeCachedOp(MXTPUHandle handle) {
  MXTPU_TCALL("free_cached_op", a.u(handle));
}

int MXTPUInvokeCachedOp(MXTPUHandle handle, int num_inputs,
                        const MXTPUHandle* inputs, int* num_outputs,
                        MXTPUHandle** outputs) {
  MXTPU_TCALL("invoke_cached_op",
              a.u(handle).i(num_inputs).p(inputs).p(num_outputs).p(outputs)
                  .u(0));
}

int MXTPUInvokeCachedOpEx(MXTPUHandle handle, int num_inputs,
                          const MXTPUHandle* inputs, int* num_outputs,
                          MXTPUHandle** outputs, const int** out_stypes) {
  MXTPU_TCALL("invoke_cached_op",
              a.u(handle).i(num_inputs).p(inputs).p(num_outputs).p(outputs)
                  .p(out_stypes));
}

/* ------------------------------------------------------------- data iter */

int MXTPUListDataIters(uint32_t* out_size, MXTPUHandle** out_array) {
  MXTPU_TCALL("list_data_iters", a.p(out_size).p(out_array));
}

int MXTPUDataIterGetIterInfo(MXTPUHandle creator, const char** name,
                             const char** description, uint32_t* num_args,
                             const char*** arg_names, const char*** arg_types,
                             const char*** arg_descriptions) {
  MXTPU_TCALL("data_iter_get_iter_info",
              a.u(creator).p(name).p(description).p(num_args).p(arg_names)
                  .p(arg_types).p(arg_descriptions));
}

int MXTPUDataIterCreateIter(MXTPUHandle creator, uint32_t num_param,
                            const char** keys, const char** vals,
                            MXTPUHandle* out) {
  MXTPU_TCALL("data_iter_create",
              a.u(creator).u(num_param).p(keys).p(vals).p(out));
}

int MXTPUDataIterFree(MXTPUHandle handle) {
  MXTPU_TCALL("data_iter_free", a.u(handle));
}

int MXTPUDataIterNext(MXTPUHandle handle, int* out) {
  MXTPU_TCALL("data_iter_next", a.u(handle).p(out));
}

int MXTPUDataIterBeforeFirst(MXTPUHandle handle) {
  MXTPU_TCALL("data_iter_before_first", a.u(handle));
}

int MXTPUDataIterGetData(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("data_iter_get_data", a.u(handle).p(out));
}

int MXTPUDataIterGetLabel(MXTPUHandle handle, MXTPUHandle* out) {
  MXTPU_TCALL("data_iter_get_label", a.u(handle).p(out));
}

int MXTPUDataIterGetIndex(MXTPUHandle handle, uint64_t** out_index,
                          uint64_t* out_size) {
  MXTPU_TCALL("data_iter_get_index", a.u(handle).p(out_index).p(out_size));
}

int MXTPUDataIterGetPadNum(MXTPUHandle handle, int* pad) {
  MXTPU_TCALL("data_iter_get_pad_num", a.u(handle).p(pad));
}

/* --------------------------------------------------------------- kvstore */

int MXTPUKVStoreCreate(const char* type, MXTPUHandle* out) {
  MXTPU_TCALL("kv_create", a.p(type).p(out));
}

int MXTPUKVStoreFree(MXTPUHandle handle) {
  MXTPU_TCALL("kv_free", a.u(handle));
}

int MXTPUKVStoreInit(MXTPUHandle handle, uint32_t num, const int* keys,
                     const MXTPUHandle* vals) {
  MXTPU_TCALL("kv_init", a.u(handle).u(num).p(keys).i(0).p(vals));
}

int MXTPUKVStoreInitEx(MXTPUHandle handle, uint32_t num, const char** keys,
                       const MXTPUHandle* vals) {
  MXTPU_TCALL("kv_init", a.u(handle).u(num).p(keys).i(1).p(vals));
}

int MXTPUKVStorePush(MXTPUHandle handle, uint32_t num, const int* keys,
                     const MXTPUHandle* vals, int priority) {
  MXTPU_TCALL("kv_push", a.u(handle).u(num).p(keys).i(0).p(vals).i(priority));
}

int MXTPUKVStorePushEx(MXTPUHandle handle, uint32_t num, const char** keys,
                       const MXTPUHandle* vals, int priority) {
  MXTPU_TCALL("kv_push", a.u(handle).u(num).p(keys).i(1).p(vals).i(priority));
}

int MXTPUKVStorePull(MXTPUHandle handle, uint32_t num, const int* keys,
                     MXTPUHandle* vals, int priority) {
  MXTPU_TCALL("kv_pull",
              a.u(handle).u(num).p(keys).i(0).p(vals).i(priority).i(1));
}

int MXTPUKVStorePullEx(MXTPUHandle handle, uint32_t num, const char** keys,
                       MXTPUHandle* vals, int priority) {
  MXTPU_TCALL("kv_pull",
              a.u(handle).u(num).p(keys).i(1).p(vals).i(priority).i(1));
}

int MXTPUKVStorePullWithSparse(MXTPUHandle handle, uint32_t num,
                               const int* keys, MXTPUHandle* vals,
                               int priority, int ignore_sparse) {
  MXTPU_TCALL("kv_pull", a.u(handle).u(num).p(keys).i(0).p(vals).i(priority)
                             .i(ignore_sparse));
}

int MXTPUKVStorePullWithSparseEx(MXTPUHandle handle, uint32_t num,
                                 const char** keys, MXTPUHandle* vals,
                                 int priority, int ignore_sparse) {
  MXTPU_TCALL("kv_pull", a.u(handle).u(num).p(keys).i(1).p(vals).i(priority)
                             .i(ignore_sparse));
}

int MXTPUKVStorePullRowSparse(MXTPUHandle handle, uint32_t num,
                              const int* keys, MXTPUHandle* vals,
                              const MXTPUHandle* row_ids, int priority) {
  MXTPU_TCALL("kv_pull_row_sparse",
              a.u(handle).u(num).p(keys).i(0).p(vals).p(row_ids).i(priority));
}

int MXTPUKVStorePullRowSparseEx(MXTPUHandle handle, uint32_t num,
                                const char** keys, MXTPUHandle* vals,
                                const MXTPUHandle* row_ids, int priority) {
  MXTPU_TCALL("kv_pull_row_sparse",
              a.u(handle).u(num).p(keys).i(1).p(vals).p(row_ids).i(priority));
}

int MXTPUKVStoreSetUpdater(MXTPUHandle handle, MXTPUKVStoreUpdater updater,
                           void* updater_handle) {
  MXTPU_TCALL("kv_set_updater",
              a.u(handle).p((void*)updater).u(0).p(updater_handle));
}

int MXTPUKVStoreSetUpdaterEx(MXTPUHandle handle, MXTPUKVStoreUpdater updater,
                             MXTPUKVStoreStrUpdater str_updater,
                             void* updater_handle) {
  MXTPU_TCALL("kv_set_updater", a.u(handle).p((void*)updater)
                                    .p((void*)str_updater).p(updater_handle));
}

int MXTPUKVStoreGetType(MXTPUHandle handle, const char** type) {
  MXTPU_TCALL("kv_get_type", a.u(handle).p(type));
}

int MXTPUKVStoreGetRank(MXTPUHandle handle, int* rank) {
  MXTPU_TCALL("kv_get_rank", a.u(handle).p(rank));
}

int MXTPUKVStoreGetGroupSize(MXTPUHandle handle, int* size) {
  MXTPU_TCALL("kv_get_group_size", a.u(handle).p(size));
}

int MXTPUKVStoreBarrier(MXTPUHandle handle) {
  MXTPU_TCALL("kv_barrier", a.u(handle));
}

int MXTPUKVStoreIsWorkerNode(int* out) {
  MXTPU_TCALL("kv_is_worker_node", a.p(out));
}

int MXTPUKVStoreIsServerNode(int* out) {
  MXTPU_TCALL("kv_is_server_node", a.p(out));
}

int MXTPUKVStoreIsSchedulerNode(int* out) {
  MXTPU_TCALL("kv_is_scheduler_node", a.p(out));
}

int MXTPUKVStoreRunServer(MXTPUHandle handle,
                          MXTPUKVStoreServerController controller,
                          void* controller_handle) {
  MXTPU_TCALL("kv_run_server",
              a.u(handle).p((void*)controller).p(controller_handle));
}

int MXTPUKVStoreSendCommmandToServers(MXTPUHandle handle, int cmd_id,
                                      const char* cmd_body) {
  MXTPU_TCALL("kv_send_command_to_servers", a.u(handle).i(cmd_id).p(cmd_body));
}

int MXTPUKVStoreSetBarrierBeforeExit(MXTPUHandle handle, int do_barrier) {
  MXTPU_TCALL("kv_set_barrier_before_exit", a.u(handle).i(do_barrier));
}

int MXTPUKVStoreGetNumDeadNode(MXTPUHandle handle, int node_id, int* number,
                               int timeout_sec) {
  MXTPU_TCALL("kv_get_num_dead_node",
              a.u(handle).i(node_id).p(number).i(timeout_sec));
}

int MXTPUKVStoreSetGradientCompression(MXTPUHandle handle,
                                       uint32_t num_params, const char** keys,
                                       const char** vals) {
  MXTPU_TCALL("kv_set_gradient_compression",
              a.u(handle).u(num_params).p(keys).p(vals));
}

int MXTPUInitPSEnv(uint32_t num_vars, const char** keys, const char** vals) {
  MXTPU_TCALL("init_ps_env", a.u(num_vars).p(keys).p(vals));
}

/* -------------------------------------------------------------- profiler */

int MXTPUSetProfilerConfig(int num_params, const char** keys,
                           const char** vals) {
  MXTPU_TCALL("profiler_set_config", a.i(num_params).p(keys).p(vals).u(0));
}

int MXTPUSetProcessProfilerConfig(int num_params, const char** keys,
                                  const char** vals,
                                  MXTPUHandle kvstore_handle) {
  MXTPU_TCALL("profiler_set_config",
              a.i(num_params).p(keys).p(vals).u(kvstore_handle));
}

int MXTPUSetProfilerState(int state) {
  MXTPU_TCALL("profiler_set_state", a.i(state).i(0));
}

int MXTPUSetProcessProfilerState(int state, int profile_process) {
  MXTPU_TCALL("profiler_set_state", a.i(state).i(profile_process));
}

int MXTPUDumpProfile(int finished) {
  MXTPU_TCALL("profiler_dump", a.i(finished).i(0));
}

int MXTPUDumpProcessProfile(int finished, int profile_process) {
  MXTPU_TCALL("profiler_dump", a.i(finished).i(profile_process));
}

int MXTPUAggregateProfileStatsPrint(const char** out_str, int reset) {
  MXTPU_TCALL("profiler_aggregate_stats_print", a.p(out_str).i(reset));
}

int MXTPUProfilePause(int paused) {
  MXTPU_TCALL("profiler_pause", a.i(paused).i(0));
}

int MXTPUProcessProfilePause(int paused, int profile_process) {
  MXTPU_TCALL("profiler_pause", a.i(paused).i(profile_process));
}

int MXTPUProfileCreateDomain(const char* domain, MXTPUHandle* out) {
  MXTPU_TCALL("profile_create_domain", a.p(domain).p(out));
}

int MXTPUProfileCreateTask(MXTPUHandle domain, const char* task_name,
                           MXTPUHandle* out) {
  MXTPU_TCALL("profile_create_task", a.u(domain).p(task_name).p(out));
}

int MXTPUProfileCreateFrame(MXTPUHandle domain, const char* frame_name,
                            MXTPUHandle* out) {
  MXTPU_TCALL("profile_create_frame", a.u(domain).p(frame_name).p(out));
}

int MXTPUProfileCreateEvent(const char* event_name, MXTPUHandle* out) {
  MXTPU_TCALL("profile_create_event", a.p(event_name).p(out));
}

int MXTPUProfileCreateCounter(MXTPUHandle domain, const char* counter_name,
                              MXTPUHandle* out) {
  MXTPU_TCALL("profile_create_counter", a.u(domain).p(counter_name).p(out));
}

int MXTPUProfileDestroyHandle(MXTPUHandle frame_handle) {
  MXTPU_TCALL("profile_destroy_handle", a.u(frame_handle));
}

int MXTPUProfileDurationStart(MXTPUHandle duration_handle) {
  MXTPU_TCALL("profile_duration_start", a.u(duration_handle));
}

int MXTPUProfileDurationStop(MXTPUHandle duration_handle) {
  MXTPU_TCALL("profile_duration_stop", a.u(duration_handle));
}

int MXTPUProfileSetCounter(MXTPUHandle counter_handle, uint64_t value) {
  MXTPU_TCALL("profile_set_counter", a.u(counter_handle).u(value));
}

int MXTPUProfileAdjustCounter(MXTPUHandle counter_handle, int64_t delta) {
  MXTPU_TCALL("profile_adjust_counter", a.u(counter_handle).i(delta));
}

int MXTPUProfileSetMarker(MXTPUHandle domain, const char* instant_name,
                          const char* scope) {
  MXTPU_TCALL("profile_set_marker", a.u(domain).p(instant_name).p(scope));
}
