/* mxtpu/c_api.h — the public C ABI of libmxtpu.
 *
 * TPU-native counterpart of the reference's include/mxnet/c_api.h (196
 * MXNET_DLL functions): the FFI seam every non-Python language binds
 * against.  Two layers back it:
 *   - the native host runtime (engine, RecordIO, data pipeline), linked
 *     directly into libmxtpu — see the Engine/Record/Pipeline groups;
 *   - the jax/XLA tensor runtime, reached through an embedded CPython
 *     interpreter (src/embed.cc) that drives the mxnet_tpu package —
 *     see the NDArray/Symbol/Executor/KVStore/... groups.  On TPU the
 *     tensor engine IS jax/XLA/PJRT, so the ABI hosts the interpreter
 *     instead of maintaining a second compute engine.
 *
 * Conventions (mirroring the reference):
 *   - every function returns 0 on success, -1 on failure; the failure
 *     message is retrieved with MXTPUGetLastError() (thread-local);
 *   - tensor-runtime handles (MXTPUHandle) are opaque uint64 ids owned
 *     by a registry inside the embedded interpreter — NOT pointers; 0
 *     is never a valid handle;
 *   - out-pointers to strings/arrays point into per-thread pinned
 *     storage owned by the runtime, valid until 256 further ABI calls
 *     are made on the same thread (the reference's thread-local return
 *     store has the same next-call invalidation contract; copy out if
 *     you need longer lifetime);
 *   - dev_type uses the reference encoding: 1=cpu, 2=gpu(accelerator →
 *     TPU here), 3=cpu_pinned; dtype uses the reference type codes
 *     (0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64);
 *   - grad_req: 0=null 1=write 3=add (reference: include/mxnet/
 *     op_attr_types.h OpReqType);
 *   - storage types: 0=default(dense) 1=row_sparse 2=csr.
 *
 * First call from a non-Python process initializes the interpreter;
 * set MXTPU_PYTHONPATH so mxnet_tpu and jax resolve (see embed.cc).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* All ABI functions are exported with default visibility even when the
 * library builds with -fvisibility=hidden. */
#ifndef MXTPU_DLL
#ifdef __GNUC__
#define MXTPU_DLL __attribute__((visibility("default")))
#else
#define MXTPU_DLL
#endif
#endif

/* Opaque tensor-runtime handle (NDArray, Symbol, Executor, DataIter,
 * KVStore, CachedOp, Op/creator, profiler object). */
typedef uint64_t MXTPUHandle;

/* ------------------------------------------------------------------ base */
/* Thread-local message for the last failed call on this thread. */
MXTPU_DLL extern const char* MXTPUGetLastError(void);
/* Library version as major*10000 + minor*100 + patch
 * (reference: MXGetVersion). */
MXTPU_DLL extern int MXTPUGetVersion(int* out);
/* Seed every device RNG (reference: MXRandomSeed). */
MXTPU_DLL extern int MXTPURandomSeed(int seed);
/* Seed the RNG of one context (reference: MXRandomSeedContext). */
MXTPU_DLL extern int MXTPURandomSeedContext(int seed, int dev_type, int dev_id);
/* Flush pending async work before process exit
 * (reference: MXNotifyShutdown). */
MXTPU_DLL extern int MXTPUNotifyShutdown(void);
/* Host-thread hint; recorded, XLA owns threading
 * (reference: MXSetNumOMPThreads). */
MXTPU_DLL extern int MXTPUSetNumOMPThreads(int nthreads);
/* Engine op-bulking hint; returns previous size
 * (reference: MXEngineSetBulkSize). */
MXTPU_DLL extern int MXTPUEngineSetBulkSize(int bulk_size, int* prev_bulk_size);
/* Number of visible accelerator devices (reference: MXGetGPUCount). */
MXTPU_DLL extern int MXTPUGetDeviceCount(int* out);
/* Free/total device memory in bytes
 * (reference: MXGetGPUMemoryInformation64). */
MXTPU_DLL extern int MXTPUGetDeviceMemoryInformation(int dev_id, uint64_t* free_mem,
                                           uint64_t* total_mem);
/* Runtime feature names + enabled flags as parallel arrays
 * (reference: MXLibInfoFeatures). */
MXTPU_DLL extern int MXTPULibInfoFeatures(const char*** out_names,
                                const int** out_enabled, uint64_t* out_size);

/* --------------------------------------------------------------- ndarray */
/* (reference: MXNDArrayCreateNone .. MXNDArrayGetGrad,
 *  src/c_api/c_api.cc) */
MXTPU_DLL extern int MXTPUNDArrayCreateNone(MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayCreate(const uint32_t* shape, uint32_t ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayCreateEx(const uint32_t* shape, uint32_t ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayFree(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUNDArrayGetShape(MXTPUHandle handle, uint32_t* out_ndim,
                                const uint32_t** out_pdata);
MXTPU_DLL extern int MXTPUNDArrayGetDType(MXTPUHandle handle, int* out);
MXTPU_DLL extern int MXTPUNDArrayGetContext(MXTPUHandle handle, int* out_dev_type,
                                  int* out_dev_id);
/* Pointer to a host snapshot of the contents (row-major, dtype above);
 * valid under the pinned-storage contract.  The reference returns the
 * live CPU buffer; device arrays here live in PJRT, so this is a read
 * snapshot — write through MXTPUNDArraySyncCopyFromCPU. */
MXTPU_DLL extern int MXTPUNDArrayGetData(MXTPUHandle handle, void** out_pdata);
MXTPU_DLL extern int MXTPUNDArraySyncCopyFromCPU(MXTPUHandle handle, const void* data,
                                       uint64_t size);
MXTPU_DLL extern int MXTPUNDArraySyncCopyToCPU(MXTPUHandle handle, void* data,
                                     uint64_t size);
/* Copy src into dst (dst keeps its dtype/context).  i selects an aux
 * array of src when >= 0 (reference: MXNDArraySyncCopyFromNDArray). */
MXTPU_DLL extern int MXTPUNDArraySyncCopyFromNDArray(MXTPUHandle dst, MXTPUHandle src,
                                           int i);
MXTPU_DLL extern int MXTPUNDArraySlice(MXTPUHandle handle, uint32_t slice_begin,
                             uint32_t slice_end, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayAt(MXTPUHandle handle, uint32_t idx, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayReshape(MXTPUHandle handle, int ndim, const int* dims,
                               MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayReshape64(MXTPUHandle handle, int ndim,
                                 const int64_t* dims, int reverse,
                                 MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayDetach(MXTPUHandle handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArraySetGradState(MXTPUHandle handle, int state);
MXTPU_DLL extern int MXTPUNDArrayGetGradState(MXTPUHandle handle, int* out);
/* *out = 0 when no gradient buffer is attached. */
MXTPU_DLL extern int MXTPUNDArrayGetGrad(MXTPUHandle handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayWaitToRead(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUNDArrayWaitToWrite(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUNDArrayWaitAll(void);
/* Serialization (reference .params container format, MXNDArraySave /
 * MXNDArrayLoad / MXNDArrayLoadFromBuffer / Save-LoadRawBytes). keys
 * may be NULL to save positionally. */
MXTPU_DLL extern int MXTPUNDArraySave(const char* fname, uint32_t num_args,
                            const MXTPUHandle* args, const char** keys);
MXTPU_DLL extern int MXTPUNDArrayLoad(const char* fname, uint32_t* out_size,
                            MXTPUHandle** out_arr, uint32_t* out_name_size,
                            const char*** out_names);
MXTPU_DLL extern int MXTPUNDArrayLoadFromBuffer(const void* ndarray_buffer,
                                      uint64_t size, uint32_t* out_size,
                                      MXTPUHandle** out_arr,
                                      uint32_t* out_name_size,
                                      const char*** out_names);
MXTPU_DLL extern int MXTPUNDArraySaveRawBytes(MXTPUHandle handle, uint64_t* out_size,
                                    const char** out_buf);
MXTPU_DLL extern int MXTPUNDArrayLoadFromRawBytes(const void* buf, uint64_t size,
                                        MXTPUHandle* out);
/* Sparse (reference: MXNDArrayCreateSparseEx, GetStorageType, GetAux*,
 * GetDataNDArray, SyncCheckFormat).  storage_type/aux layout follows
 * the reference: row_sparse aux0=indices; csr aux0=indptr aux1=indices. */
MXTPU_DLL extern int MXTPUNDArrayGetStorageType(MXTPUHandle handle, int* out);
MXTPU_DLL extern int MXTPUNDArrayCreateSparseEx(
    int storage_type, const uint32_t* shape, uint32_t ndim, int dev_type,
    int dev_id, int delay_alloc, int dtype, uint32_t num_aux,
    const int* aux_type, const uint32_t* aux_ndims, const uint32_t* aux_shape,
    MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayGetAuxType(MXTPUHandle handle, uint32_t i, int* out);
MXTPU_DLL extern int MXTPUNDArrayGetAuxNDArray(MXTPUHandle handle, uint32_t i,
                                     MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayGetDataNDArray(MXTPUHandle handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArraySyncCheckFormat(MXTPUHandle handle, int full_check);
/* DLPack interop (reference: MXNDArrayToDLPack/FromDLPack/
 * CallDLPackDeleter).  ToDLPack exports a host snapshot as a
 * DLManagedTensor*; the consumer must call its deleter (or
 * MXTPUNDArrayCallDLPackDeleter).  FromDLPack copies out of the tensor
 * and calls its deleter. */
MXTPU_DLL extern int MXTPUNDArrayToDLPack(MXTPUHandle handle, void** out_dlmanaged);
MXTPU_DLL extern int MXTPUNDArrayFromDLPack(void* dlmanaged, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUNDArrayCallDLPackDeleter(void* dlmanaged);
/* POSIX shared-memory interop (reference: MXNDArrayGetSharedMemHandle /
 * MXNDArrayCreateFromSharedMem, used by the multiprocess DataLoader). */
MXTPU_DLL extern int MXTPUNDArrayGetSharedMemHandle(MXTPUHandle handle, int* shared_pid,
                                          int* shared_id);
MXTPU_DLL extern int MXTPUNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                           const uint32_t* shape,
                                           uint32_t ndim, int dtype,
                                           MXTPUHandle* out);

/* ------------------------------------------------- ops & imperative call */
/* (reference: MXListAllOpNames, NNGetOpHandle, MXSymbolGetAtomicSymbolInfo,
 *  MXImperativeInvoke; also backs the legacy MXFunc* surface) */
MXTPU_DLL extern int MXTPUListAllOpNames(uint32_t* out_size, const char*** out_array);
MXTPU_DLL extern int MXTPUGetOpHandle(const char* op_name, MXTPUHandle* out);
/* Full signature info for an op/creator handle.  arg_types are python
 * repr strings of the default ("<required>" when none). */
MXTPU_DLL extern int MXTPUGetOpInfo(MXTPUHandle op, const char** name,
                          const char** description, uint32_t* num_args,
                          const char*** arg_names, const char*** arg_types,
                          const char*** arg_descriptions,
                          const char** return_type);
/* Invoke an op on NDArray inputs.  If *num_outputs==0 the runtime
 * allocates outputs and returns new handles in *outputs (pinned array);
 * if the caller provides *num_outputs>0 and *outputs, results are
 * written into those arrays in place (reference: MXImperativeInvoke). */
MXTPU_DLL extern int MXTPUImperativeInvoke(MXTPUHandle op, int num_inputs,
                                 const MXTPUHandle* inputs, int* num_outputs,
                                 MXTPUHandle** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals);
/* Legacy function surface (reference: MXListFunctions/MXGetFunction/
 * MXFuncGetInfo/MXFuncInvokeEx): functions ARE op handles here. */
MXTPU_DLL extern int MXTPUListFunctions(uint32_t* out_size, MXTPUHandle** out_array);
MXTPU_DLL extern int MXTPUGetFunction(const char* name, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUFuncGetInfo(MXTPUHandle fun, const char** name,
                            const char** description, uint32_t* num_args,
                            const char*** arg_names, const char*** arg_types,
                            const char*** arg_descriptions,
                            const char** return_type);
/* use_vars are inputs, mutate_vars receive the outputs; a single scalar
 * arg is passed to the op's scalar parameter (reference semantics for
 * the *_scalar family). */
MXTPU_DLL extern int MXTPUFuncInvoke(MXTPUHandle fun, const MXTPUHandle* use_vars,
                           const float* scalar_args,
                           const MXTPUHandle* mutate_vars, int num_use,
                           int num_scalar, int num_mutate);
MXTPU_DLL extern int MXTPUFuncInvokeEx(MXTPUHandle fun, const MXTPUHandle* use_vars,
                             const float* scalar_args,
                             const MXTPUHandle* mutate_vars, int num_use,
                             int num_scalar, int num_mutate, int num_params,
                             const char** param_keys,
                             const char** param_vals);

/* -------------------------------------------------------------- autograd */
/* (reference: MXAutogradSetIsRecording .. MXAutogradGetSymbol) */
MXTPU_DLL extern int MXTPUAutogradSetIsRecording(int is_recording, int* prev);
MXTPU_DLL extern int MXTPUAutogradSetIsTraining(int is_training, int* prev);
MXTPU_DLL extern int MXTPUAutogradIsRecording(int* curr);
MXTPU_DLL extern int MXTPUAutogradIsTraining(int* curr);
/* reqs use grad_req codes (0 null / 1 write / 3 add). */
MXTPU_DLL extern int MXTPUAutogradMarkVariables(uint32_t num_var,
                                      const MXTPUHandle* var_handles,
                                      const uint32_t* reqs_array,
                                      const MXTPUHandle* grad_handles);
MXTPU_DLL extern int MXTPUAutogradBackward(uint32_t num_output,
                                 const MXTPUHandle* output_handles,
                                 const MXTPUHandle* ograd_handles,
                                 int retain_graph);
/* With num_variables>0 returns the gradients w.r.t. those variables in
 * *grad_handles (+ storage types); otherwise gradients accumulate into
 * the marked variables' grad buffers. */
MXTPU_DLL extern int MXTPUAutogradBackwardEx(uint32_t num_output,
                                   const MXTPUHandle* output_handles,
                                   const MXTPUHandle* ograd_handles,
                                   uint32_t num_variables,
                                   const MXTPUHandle* var_handles,
                                   int retain_graph, int create_graph,
                                   int is_train, MXTPUHandle** grad_handles,
                                   const int** grad_stypes);
MXTPU_DLL extern int MXTPUAutogradComputeGradient(uint32_t num_output,
                                        const MXTPUHandle* output_handles);
MXTPU_DLL extern int MXTPUAutogradGetSymbol(MXTPUHandle ndhandle, MXTPUHandle* out);

/* ---------------------------------------------------------------- symbol */
/* (reference: MXSymbolListAtomicSymbolCreators .. MXSymbolInferType,
 *  src/c_api/c_api_symbolic.cc) */
MXTPU_DLL extern int MXTPUSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                               MXTPUHandle** out_array);
MXTPU_DLL extern int MXTPUSymbolGetAtomicSymbolName(MXTPUHandle creator,
                                          const char** name);
MXTPU_DLL extern int MXTPUSymbolGetAtomicSymbolInfo(
    MXTPUHandle creator, const char** name, const char** description,
    uint32_t* num_args, const char*** arg_names, const char*** arg_types,
    const char*** arg_descriptions, const char** key_var_num_args,
    const char** return_type);
MXTPU_DLL extern int MXTPUSymbolCreateAtomicSymbol(MXTPUHandle creator,
                                         uint32_t num_param,
                                         const char** keys, const char** vals,
                                         MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolCreateVariable(const char* name, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolCreateGroup(uint32_t num_symbols,
                                  const MXTPUHandle* symbols,
                                  MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolCreateFromFile(const char* fname, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolCreateFromJSON(const char* json, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolSaveToFile(MXTPUHandle symbol, const char* fname);
MXTPU_DLL extern int MXTPUSymbolSaveToJSON(MXTPUHandle symbol, const char** out_json);
MXTPU_DLL extern int MXTPUSymbolFree(MXTPUHandle symbol);
MXTPU_DLL extern int MXTPUSymbolCopy(MXTPUHandle symbol, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolPrint(MXTPUHandle symbol, const char** out_str);
MXTPU_DLL extern int MXTPUSymbolGetName(MXTPUHandle symbol, const char** out,
                              int* success);
MXTPU_DLL extern int MXTPUSymbolGetAttr(MXTPUHandle symbol, const char* key,
                              const char** out, int* success);
MXTPU_DLL extern int MXTPUSymbolSetAttr(MXTPUHandle symbol, const char* key,
                              const char* value);
/* key/value pairs flattened as [k0, v0, k1, v1, ...] (out_size = number
 * of pairs), deep (ListAttr) or node-local (ListAttrShallow). */
MXTPU_DLL extern int MXTPUSymbolListAttr(MXTPUHandle symbol, uint32_t* out_size,
                               const char*** out);
MXTPU_DLL extern int MXTPUSymbolListAttrShallow(MXTPUHandle symbol, uint32_t* out_size,
                                      const char*** out);
MXTPU_DLL extern int MXTPUSymbolListArguments(MXTPUHandle symbol, uint32_t* out_size,
                                    const char*** out_str_array);
MXTPU_DLL extern int MXTPUSymbolListOutputs(MXTPUHandle symbol, uint32_t* out_size,
                                  const char*** out_str_array);
MXTPU_DLL extern int MXTPUSymbolListAuxiliaryStates(MXTPUHandle symbol,
                                          uint32_t* out_size,
                                          const char*** out_str_array);
MXTPU_DLL extern int MXTPUSymbolGetNumOutputs(MXTPUHandle symbol, uint32_t* output_count);
MXTPU_DLL extern int MXTPUSymbolGetInternals(MXTPUHandle symbol, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolGetChildren(MXTPUHandle symbol, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolGetOutput(MXTPUHandle symbol, uint32_t index,
                                MXTPUHandle* out);
MXTPU_DLL extern int MXTPUSymbolGetInputSymbols(MXTPUHandle symbol,
                                      MXTPUHandle** out_handles,
                                      uint32_t* out_size);
/* Compose positionally (keys NULL) or by name. */
MXTPU_DLL extern int MXTPUSymbolCompose(MXTPUHandle symbol, const char* name,
                              uint32_t num_args, const char** keys,
                              const MXTPUHandle* args);
/* Shape inference.  Provided shapes keyed (keys!=NULL) or positional;
 * CSR-style (arg_ind_ptr, arg_shape_data) packing.  Results come back
 * as three pinned (size, ndims[], data[][]) triples for arguments /
 * outputs / aux states (reference: MXSymbolInferShape). */
MXTPU_DLL extern int MXTPUSymbolInferShape(
    MXTPUHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete);
MXTPU_DLL extern int MXTPUSymbolInferShapePartial(
    MXTPUHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete);
MXTPU_DLL extern int MXTPUSymbolInferType(MXTPUHandle sym, uint32_t num_args,
                                const char** keys, const int* arg_type_data,
                                uint32_t* in_type_size,
                                const int** in_type_data,
                                uint32_t* out_type_size,
                                const int** out_type_data,
                                uint32_t* aux_type_size,
                                const int** aux_type_data, int* complete);
/* Graph passes (reference: MXQuantizeSymbol,
 * MXSetCalibTableToQuantizedSymbol, MXGenBackendSubgraph). */
MXTPU_DLL extern int MXTPUQuantizeSymbol(MXTPUHandle sym, MXTPUHandle* out,
                               uint32_t num_excluded,
                               const char** excluded_op_names,
                               const char* quantized_dtype);
MXTPU_DLL extern int MXTPUSetCalibTableToQuantizedSymbol(
    MXTPUHandle qsym, uint32_t num_layers, const char** layer_names,
    const float* low_quantiles, const float* high_quantiles, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUGenBackendSubgraph(MXTPUHandle sym, const char* backend,
                                   MXTPUHandle* out);

/* -------------------------------------------------------------- executor */
/* (reference: MXExecutorBind .. MXExecutorSetMonitorCallbackEX,
 *  src/c_api/c_api_executor.cc) */
typedef void (*MXTPUExecutorMonitorCallback)(const char* name,
                                             MXTPUHandle ndarray,
                                             void* callback_ctx);
MXTPU_DLL extern int MXTPUExecutorFree(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUExecutorPrint(MXTPUHandle handle, const char** out_str);
MXTPU_DLL extern int MXTPUExecutorForward(MXTPUHandle handle, int is_train);
MXTPU_DLL extern int MXTPUExecutorBackward(MXTPUHandle handle, uint32_t len,
                                 const MXTPUHandle* head_grads);
MXTPU_DLL extern int MXTPUExecutorBackwardEx(MXTPUHandle handle, uint32_t len,
                                   const MXTPUHandle* head_grads,
                                   int is_train);
MXTPU_DLL extern int MXTPUExecutorOutputs(MXTPUHandle handle, uint32_t* out_size,
                                MXTPUHandle** out);
/* grad_req_type uses grad_req codes; arg_grad_store entries may be 0
 * for no-gradient arguments. */
MXTPU_DLL extern int MXTPUExecutorBind(MXTPUHandle symbol_handle, int dev_type,
                             int dev_id, uint32_t len,
                             const MXTPUHandle* in_args,
                             const MXTPUHandle* arg_grad_store,
                             const uint32_t* grad_req_type, uint32_t aux_len,
                             const MXTPUHandle* aux_states, MXTPUHandle* out);
/* Group-to-context variants: the maps are accepted and recorded; XLA
 * owns placement on the single-process device, so they do not change
 * execution (documented narrowing). */
MXTPU_DLL extern int MXTPUExecutorBindX(MXTPUHandle symbol_handle, int dev_type,
                              int dev_id, uint32_t num_map_keys,
                              const char** map_keys,
                              const int* map_dev_types,
                              const int* map_dev_ids, uint32_t len,
                              const MXTPUHandle* in_args,
                              const MXTPUHandle* arg_grad_store,
                              const uint32_t* grad_req_type,
                              uint32_t aux_len,
                              const MXTPUHandle* aux_states,
                              MXTPUHandle* out);
MXTPU_DLL extern int MXTPUExecutorBindEX(MXTPUHandle symbol_handle, int dev_type,
                               int dev_id, uint32_t num_map_keys,
                               const char** map_keys,
                               const int* map_dev_types,
                               const int* map_dev_ids, uint32_t len,
                               const MXTPUHandle* in_args,
                               const MXTPUHandle* arg_grad_store,
                               const uint32_t* grad_req_type,
                               uint32_t aux_len,
                               const MXTPUHandle* aux_states,
                               MXTPUHandle shared_exec, MXTPUHandle* out);
/* Allocate-and-bind: shapes/dtypes/stypes/grad-reqs provided by name;
 * returns the allocated in_args/arg_grads/aux_states handle arrays
 * (pinned).  g2c maps and shared-buffer params are accepted for ABI
 * parity; sharing is keyed by shared_exec (reference:
 * MXExecutorSimpleBindEx). */
MXTPU_DLL extern int MXTPUExecutorSimpleBind(
    MXTPUHandle symbol_handle, int dev_type, int dev_id,
    uint32_t num_g2c_keys, const char** g2c_keys, const int* g2c_dev_types,
    const int* g2c_dev_ids, uint32_t provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    uint32_t num_provided_arg_shapes, const char** provided_arg_shape_names,
    const uint32_t* provided_arg_shape_data,
    const uint32_t* provided_arg_shape_idx, uint32_t num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    uint32_t num_provided_arg_stypes, const char** provided_arg_stype_names,
    const int* provided_arg_stypes, uint32_t num_shared_arg_names,
    const char** shared_arg_name_list, int* shared_buffer_len,
    const char** shared_buffer_name_list,
    const MXTPUHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    MXTPUHandle** updated_shared_buffer_handle_list, uint32_t* num_in_args,
    MXTPUHandle** in_args, MXTPUHandle** arg_grads, uint32_t* num_aux_states,
    MXTPUHandle** aux_states, MXTPUHandle shared_exec_handle,
    MXTPUHandle* out);
MXTPU_DLL extern int MXTPUExecutorReshape(int partial_shaping, int allow_up_sizing,
                                int dev_type, int dev_id,
                                uint32_t num_map_keys, const char** map_keys,
                                const int* map_dev_types,
                                const int* map_dev_ids,
                                uint32_t num_provided_arg_shapes,
                                const char** provided_arg_shape_names,
                                const uint32_t* provided_arg_shape_data,
                                const uint32_t* provided_arg_shape_idx,
                                uint32_t* num_in_args, MXTPUHandle** in_args,
                                MXTPUHandle** arg_grads,
                                uint32_t* num_aux_states,
                                MXTPUHandle** aux_states,
                                MXTPUHandle shared_exec, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUExecutorGetOptimizedSymbol(MXTPUHandle handle,
                                           MXTPUHandle* out);
MXTPU_DLL extern int MXTPUExecutorSetMonitorCallback(MXTPUHandle handle,
                                           MXTPUExecutorMonitorCallback cb,
                                           void* callback_ctx);
MXTPU_DLL extern int MXTPUExecutorSetMonitorCallbackEX(MXTPUHandle handle,
                                             MXTPUExecutorMonitorCallback cb,
                                             void* callback_ctx,
                                             int monitor_all);

/* ------------------------------------------------------------- cached op */
/* (reference: MXCreateCachedOp(Ex)/MXInvokeCachedOp(Ex)/MXFreeCachedOp) */
MXTPU_DLL extern int MXTPUCreateCachedOp(MXTPUHandle sym_handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUCreateCachedOpEx(MXTPUHandle sym_handle, int num_flags,
                                 const char** keys, const char** vals,
                                 MXTPUHandle* out);
MXTPU_DLL extern int MXTPUFreeCachedOp(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUInvokeCachedOp(MXTPUHandle handle, int num_inputs,
                               const MXTPUHandle* inputs, int* num_outputs,
                               MXTPUHandle** outputs);
MXTPU_DLL extern int MXTPUInvokeCachedOpEx(MXTPUHandle handle, int num_inputs,
                                 const MXTPUHandle* inputs, int* num_outputs,
                                 MXTPUHandle** outputs,
                                 const int** out_stypes);

/* -------------------------------------------------------------- data iter */
/* (reference: MXListDataIters .. MXDataIterGetPadNum,
 *  src/c_api/c_api.cc io section) */
MXTPU_DLL extern int MXTPUListDataIters(uint32_t* out_size, MXTPUHandle** out_array);
MXTPU_DLL extern int MXTPUDataIterGetIterInfo(MXTPUHandle creator, const char** name,
                                    const char** description,
                                    uint32_t* num_args,
                                    const char*** arg_names,
                                    const char*** arg_types,
                                    const char*** arg_descriptions);
MXTPU_DLL extern int MXTPUDataIterCreateIter(MXTPUHandle creator, uint32_t num_param,
                                   const char** keys, const char** vals,
                                   MXTPUHandle* out);
MXTPU_DLL extern int MXTPUDataIterFree(MXTPUHandle handle);
/* *out = 1 while more batches remain, 0 at epoch end. */
MXTPU_DLL extern int MXTPUDataIterNext(MXTPUHandle handle, int* out);
MXTPU_DLL extern int MXTPUDataIterBeforeFirst(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUDataIterGetData(MXTPUHandle handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUDataIterGetLabel(MXTPUHandle handle, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUDataIterGetIndex(MXTPUHandle handle, uint64_t** out_index,
                                 uint64_t* out_size);
MXTPU_DLL extern int MXTPUDataIterGetPadNum(MXTPUHandle handle, int* pad);

/* --------------------------------------------------------------- kvstore */
/* (reference: MXKVStoreCreate .. MXKVStoreGetNumDeadNode, MXInitPSEnv,
 *  src/c_api/c_api.cc kvstore section) */
typedef void (*MXTPUKVStoreUpdater)(int key, MXTPUHandle recv,
                                    MXTPUHandle local, void* handle);
typedef void (*MXTPUKVStoreStrUpdater)(const char* key, MXTPUHandle recv,
                                       MXTPUHandle local, void* handle);
typedef void (*MXTPUKVStoreServerController)(int head, const char* body,
                                             void* controller_handle);
MXTPU_DLL extern int MXTPUKVStoreCreate(const char* type, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUKVStoreFree(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUKVStoreInit(MXTPUHandle handle, uint32_t num, const int* keys,
                            const MXTPUHandle* vals);
MXTPU_DLL extern int MXTPUKVStoreInitEx(MXTPUHandle handle, uint32_t num,
                              const char** keys, const MXTPUHandle* vals);
MXTPU_DLL extern int MXTPUKVStorePush(MXTPUHandle handle, uint32_t num, const int* keys,
                            const MXTPUHandle* vals, int priority);
MXTPU_DLL extern int MXTPUKVStorePushEx(MXTPUHandle handle, uint32_t num,
                              const char** keys, const MXTPUHandle* vals,
                              int priority);
MXTPU_DLL extern int MXTPUKVStorePull(MXTPUHandle handle, uint32_t num, const int* keys,
                            MXTPUHandle* vals, int priority);
MXTPU_DLL extern int MXTPUKVStorePullEx(MXTPUHandle handle, uint32_t num,
                              const char** keys, MXTPUHandle* vals,
                              int priority);
MXTPU_DLL extern int MXTPUKVStorePullWithSparse(MXTPUHandle handle, uint32_t num,
                                      const int* keys, MXTPUHandle* vals,
                                      int priority, int ignore_sparse);
MXTPU_DLL extern int MXTPUKVStorePullWithSparseEx(MXTPUHandle handle, uint32_t num,
                                        const char** keys, MXTPUHandle* vals,
                                        int priority, int ignore_sparse);
MXTPU_DLL extern int MXTPUKVStorePullRowSparse(MXTPUHandle handle, uint32_t num,
                                     const int* keys, MXTPUHandle* vals,
                                     const MXTPUHandle* row_ids,
                                     int priority);
MXTPU_DLL extern int MXTPUKVStorePullRowSparseEx(MXTPUHandle handle, uint32_t num,
                                       const char** keys, MXTPUHandle* vals,
                                       const MXTPUHandle* row_ids,
                                       int priority);
MXTPU_DLL extern int MXTPUKVStoreSetUpdater(MXTPUHandle handle,
                                  MXTPUKVStoreUpdater updater,
                                  void* updater_handle);
MXTPU_DLL extern int MXTPUKVStoreSetUpdaterEx(MXTPUHandle handle,
                                    MXTPUKVStoreUpdater updater,
                                    MXTPUKVStoreStrUpdater str_updater,
                                    void* updater_handle);
MXTPU_DLL extern int MXTPUKVStoreGetType(MXTPUHandle handle, const char** type);
MXTPU_DLL extern int MXTPUKVStoreGetRank(MXTPUHandle handle, int* rank);
MXTPU_DLL extern int MXTPUKVStoreGetGroupSize(MXTPUHandle handle, int* size);
MXTPU_DLL extern int MXTPUKVStoreBarrier(MXTPUHandle handle);
MXTPU_DLL extern int MXTPUKVStoreIsWorkerNode(int* out);
MXTPU_DLL extern int MXTPUKVStoreIsServerNode(int* out);
MXTPU_DLL extern int MXTPUKVStoreIsSchedulerNode(int* out);
MXTPU_DLL extern int MXTPUKVStoreRunServer(MXTPUHandle handle,
                                 MXTPUKVStoreServerController controller,
                                 void* controller_handle);
MXTPU_DLL extern int MXTPUKVStoreSendCommmandToServers(MXTPUHandle handle, int cmd_id,
                                             const char* cmd_body);
MXTPU_DLL extern int MXTPUKVStoreSetBarrierBeforeExit(MXTPUHandle handle,
                                            int do_barrier);
MXTPU_DLL extern int MXTPUKVStoreGetNumDeadNode(MXTPUHandle handle, int node_id,
                                      int* number, int timeout_sec);
MXTPU_DLL extern int MXTPUKVStoreSetGradientCompression(MXTPUHandle handle,
                                              uint32_t num_params,
                                              const char** keys,
                                              const char** vals);
MXTPU_DLL extern int MXTPUInitPSEnv(uint32_t num_vars, const char** keys,
                          const char** vals);

/* -------------------------------------------------------------- profiler */
/* (reference: MXSetProfilerConfig .. MXProfileSetMarker,
 *  src/c_api/c_api_profile.cc) */
MXTPU_DLL extern int MXTPUSetProfilerConfig(int num_params, const char** keys,
                                  const char** vals);
MXTPU_DLL extern int MXTPUSetProcessProfilerConfig(int num_params, const char** keys,
                                         const char** vals,
                                         MXTPUHandle kvstore_handle);
/* state: 0 stop, 1 run. */
MXTPU_DLL extern int MXTPUSetProfilerState(int state);
MXTPU_DLL extern int MXTPUSetProcessProfilerState(int state, int profile_process);
MXTPU_DLL extern int MXTPUDumpProfile(int finished);
MXTPU_DLL extern int MXTPUDumpProcessProfile(int finished, int profile_process);
MXTPU_DLL extern int MXTPUAggregateProfileStatsPrint(const char** out_str, int reset);
MXTPU_DLL extern int MXTPUProfilePause(int paused);
MXTPU_DLL extern int MXTPUProcessProfilePause(int paused, int profile_process);
MXTPU_DLL extern int MXTPUProfileCreateDomain(const char* domain, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUProfileCreateTask(MXTPUHandle domain, const char* task_name,
                                  MXTPUHandle* out);
MXTPU_DLL extern int MXTPUProfileCreateFrame(MXTPUHandle domain, const char* frame_name,
                                   MXTPUHandle* out);
MXTPU_DLL extern int MXTPUProfileCreateEvent(const char* event_name, MXTPUHandle* out);
MXTPU_DLL extern int MXTPUProfileCreateCounter(MXTPUHandle domain,
                                     const char* counter_name,
                                     MXTPUHandle* out);
MXTPU_DLL extern int MXTPUProfileDestroyHandle(MXTPUHandle frame_handle);
MXTPU_DLL extern int MXTPUProfileDurationStart(MXTPUHandle duration_handle);
MXTPU_DLL extern int MXTPUProfileDurationStop(MXTPUHandle duration_handle);
MXTPU_DLL extern int MXTPUProfileSetCounter(MXTPUHandle counter_handle, uint64_t value);
MXTPU_DLL extern int MXTPUProfileAdjustCounter(MXTPUHandle counter_handle,
                                     int64_t delta);
MXTPU_DLL extern int MXTPUProfileSetMarker(MXTPUHandle domain, const char* instant_name,
                                 const char* scope);

/* ------------------------------------------------- native host runtime  */
/* Engine / RecordIO / Pipeline groups: direct C++ (no interpreter) —
 * declarations kept in sync with src/c_api.cc.  Reference analogs:
 * engine push/wait (include/mxnet/engine.h), MXRecordIO*
 * (include/mxnet/c_api.h), and the ImageRecordIter worker pipeline. */
typedef int (*MXTPUEngineOpFn)(void* ctx, uint64_t op_id);
MXTPU_DLL extern int MXTPUEngineCreate(int n_workers, int io_workers, void** out);
MXTPU_DLL extern int MXTPUEngineFree(void* h);
MXTPU_DLL extern int MXTPUEngineNewVar(void* h, uint64_t* out);
MXTPU_DLL extern int MXTPUEngineDelVar(void* h, uint64_t var);
MXTPU_DLL extern int MXTPUEnginePush(void* h, MXTPUEngineOpFn fn, void* ctx,
                           const uint64_t* cvars, int ncv,
                           const uint64_t* mvars, int nmv, int prop,
                           const char* name, uint64_t* out_op_id);
MXTPU_DLL extern int MXTPUEngineOnComplete(void* h, uint64_t op_id);
MXTPU_DLL extern int MXTPUEngineOnCompleteError(void* h, uint64_t op_id,
                                      const char* msg);
MXTPU_DLL extern int MXTPUEngineWaitForVar(void* h, uint64_t var);
MXTPU_DLL extern int MXTPUEngineWaitAll(void* h);
MXTPU_DLL extern int MXTPUEngineNumPending(void* h, int64_t* out);
MXTPU_DLL extern int MXTPURecordReaderCreate(const char* path, uint64_t chunk, int part,
                                   int nparts, void** out);
MXTPU_DLL extern int MXTPURecordReaderNext(void* h, const uint8_t** data,
                                 uint32_t* size);
MXTPU_DLL extern int MXTPURecordReaderReset(void* h);
MXTPU_DLL extern int MXTPURecordReaderSeek(void* h, uint64_t pos);
MXTPU_DLL extern int MXTPURecordReaderTell(void* h, uint64_t* pos);
MXTPU_DLL extern int MXTPURecordReaderFree(void* h);
MXTPU_DLL extern int MXTPURecordWriterCreate(const char* path, void** out);
MXTPU_DLL extern int MXTPURecordWriterWrite(void* h, const uint8_t* data, uint32_t size,
                                  uint64_t* out_pos);
MXTPU_DLL extern int MXTPURecordWriterTell(void* h, uint64_t* pos);
MXTPU_DLL extern int MXTPURecordWriterFree(void* h);
/* Prefetching batch pipeline over a .rec shard (worker pool + reorder
 * queue; reference: src/io/iter_image_recordio_2.cc).  decode fills one
 * sample slot from one record, returning 0 on success; NULL selects the
 * built-in raw decoder. */
typedef int (*MXTPUDecodeFn)(void* ctx, const uint8_t* rec, uint32_t len,
                             uint8_t* data_out, float* label_out);
MXTPU_DLL extern int MXTPUPipelineCreate(
    const char* path, uint64_t chunk_bytes, int part_index, int num_parts,
    int batch_size, uint64_t sample_bytes, int label_width, int shuffle,
    uint64_t seed, int num_workers, int queue_depth, int last_batch_keep,
    MXTPUDecodeFn decode, void* decode_ctx, void** out);
/* In-worker JPEG decode + augment variant (the img, rand, and mean
 * params describe the augment chain; fallback handles non-JPEG
 * payloads). */
MXTPU_DLL extern int MXTPUPipelineCreateJpeg(
    const char* path, uint64_t chunk_bytes, int part_index, int num_parts,
    int batch_size, uint64_t sample_bytes, int label_width, int shuffle,
    uint64_t seed, int num_workers, int queue_depth, int last_batch_keep,
    int img_h, int img_w, int img_c, int rand_crop, int rand_mirror,
    float mean_r, float mean_g, float mean_b, MXTPUDecodeFn fallback,
    void* fallback_ctx, void** out);
/* 1 when libmxtpu was built against libjpeg. */
MXTPU_DLL extern int MXTPUPipelineHasJpeg(void);
/* count is set to -1 at end of epoch. */
MXTPU_DLL extern int MXTPUPipelineNext(void* h, uint8_t** data, float** label,
                                       int* count);
MXTPU_DLL extern int MXTPUPipelineRelease(void* h, uint8_t* data, float* label);
MXTPU_DLL extern int MXTPUPipelineReset(void* h);
MXTPU_DLL extern int MXTPUPipelineFree(void* h);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_API_H_ */
