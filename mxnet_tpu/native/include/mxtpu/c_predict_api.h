/* mxtpu/c_predict_api.h — deployment (inference-only) C ABI.
 *
 * Counterpart of the reference's include/mxnet/c_predict_api.h
 * (MXPredCreate/SetInput/Forward/GetOutputShape/GetOutput/Reshape/Free),
 * kept in a separate header exactly as the reference does: a deployment
 * consumer needs only these seven functions plus MXTPUGetLastError.
 * Backed by src/predict.cc over the embedded-interpreter bridge — see
 * mxtpu/c_api.h for conventions (0/-1 returns, thread-local errors,
 * MXTPU_PYTHONPATH for non-Python hosts).
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#ifndef MXTPU_DLL
#ifdef __GNUC__
#define MXTPU_DLL __attribute__((visibility("default")))
#else
#define MXTPU_DLL
#endif
#endif

MXTPU_DLL extern const char* MXTPUGetLastError(void);

/* Load an exported model: symbol JSON + .params blob.  Input shapes are
 * CSR-packed over input_keys (indptr of length num_input_nodes+1).
 * dev_type: 1=cpu, 2=accelerator(TPU). */
MXTPU_DLL extern int MXTPUPredCreate(const char* symbol_json,
                                     const void* param_bytes,
                                     uint64_t param_size, int dev_type,
                                     int dev_id, uint32_t num_input_nodes,
                                     const char** input_keys,
                                     const uint32_t* input_shape_indptr,
                                     const uint32_t* input_shape_data,
                                     void** out);
MXTPU_DLL extern int MXTPUPredSetInput(void* handle, const char* key,
                                       const float* data, uint64_t size);
MXTPU_DLL extern int MXTPUPredForward(void* handle);
MXTPU_DLL extern int MXTPUPredGetOutputShape(void* handle, uint32_t index,
                                             const uint32_t** shape_data,
                                             uint32_t* shape_ndim);
MXTPU_DLL extern int MXTPUPredGetOutput(void* handle, uint32_t index,
                                        float* data, uint64_t size);
MXTPU_DLL extern int MXTPUPredReshape(uint32_t num_input_nodes,
                                      const char** input_keys,
                                      const uint32_t* input_shape_indptr,
                                      const uint32_t* input_shape_data,
                                      void* handle, void** out);
MXTPU_DLL extern int MXTPUPredFree(void* handle);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
