"""Evaluation metrics (reference: python/mxnet/metric.py:68 EvalMetric
registry): Accuracy, TopK, F1, MCC, MAE/MSE/RMSE, CrossEntropy, NLL,
Perplexity, PearsonCorrelation, Loss, Torch/Caffe aliases, CustomMetric,
CompositeEvalMetric, np()/create().
"""

from __future__ import annotations

import math

import numpy as _np

from .base import Registry

_REG = Registry("metric")


def register(klass):
    _REG.register(klass)
    return klass


def alias(*aliases):
    def deco(klass):
        _REG.alias(klass, *aliases)
        return klass

    return deco


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}".format(
                label_shape, pred_shape))
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


def _to_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)


class EvalMetric:
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
@alias("acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p = _to_numpy(pred)
            l = _to_numpy(label).astype("int32")
            if p.ndim > l.ndim:
                p = p.argmax(axis=self.axis)
            p = p.astype("int32").reshape(-1)
            l = l.reshape(-1)
            check_label_shapes(l, p)
            self.sum_metric += (p == l).sum()
            self.num_inst += len(p)


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy for top_k=1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p = _np.argsort(_to_numpy(pred).astype("float32"), axis=-1)
            l = _to_numpy(label).astype("int32")
            num_samples = p.shape[0]
            num_dims = len(p.shape)
            if num_dims == 1:
                self.sum_metric += (p.reshape(-1) == l.reshape(-1)).sum()
            elif num_dims == 2:
                num_classes = p.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (p[:, num_classes - 1 - j].reshape(-1)
                                        == l.reshape(-1)).sum()
            self.num_inst += num_samples


class _BinaryClassificationMetrics:
    """Shared tp/fp/tn/fn bookkeeping for F1 / MCC."""

    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred_label = _np.argmax(pred, axis=1)
        label = label.astype("int32").reshape(-1)
        if len(_np.unique(label)) > 2:
            raise ValueError("%s currently only supports binary classification."
                             % self.__class__.__name__)
        self.true_positives += ((pred_label == 1) & (label == 1)).sum()
        self.false_positives += ((pred_label == 1) & (label == 0)).sum()
        self.false_negatives += ((pred_label == 0) & (label == 1)).sum()
        self.true_negatives += ((pred_label == 0) & (label == 0)).sum()

    @property
    def precision(self):
        tp_fp = self.true_positives + self.false_positives
        return self.true_positives / tp_fp if tp_fp > 0 else 0.0

    @property
    def recall(self):
        tp_fn = self.true_positives + self.false_negatives
        return self.true_positives / tp_fn if tp_fn > 0 else 0.0

    @property
    def fscore(self):
        pr = self.precision + self.recall
        return 2 * self.precision * self.recall / pr if pr > 0 else 0.0

    @property
    def matthewscc(self):
        terms = [(self.true_positives + self.false_positives),
                 (self.true_positives + self.false_negatives),
                 (self.true_negatives + self.false_positives),
                 (self.true_negatives + self.false_negatives)]
        denom = 1.0
        for t in terms:
            denom *= t if t != 0 else 1.0
        return ((self.true_positives * self.true_negatives
                 - self.false_positives * self.false_negatives)
                / math.sqrt(denom))

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives
                + self.true_negatives + self.true_positives)

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None, average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_to_numpy(label), _to_numpy(pred))
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.num_inst = self.metrics.total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class MCC(F1):
    def __init__(self, name="mcc", output_names=None, label_names=None, average="macro"):
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names, average=average)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_to_numpy(label), _to_numpy(pred))
        if self.average == "macro":
            self.sum_metric += self.metrics.matthewscc
            self.num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.matthewscc * self.metrics.total_examples
            self.num_inst = self.metrics.total_examples


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _to_numpy(label)
            p = _to_numpy(pred)
            if len(l.shape) == 1:
                l = l.reshape(l.shape[0], 1)
            if len(p.shape) == 1:
                p = p.reshape(p.shape[0], 1)
            self.sum_metric += _np.abs(l - p).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _to_numpy(label)
            p = _to_numpy(pred)
            if len(l.shape) == 1:
                l = l.reshape(l.shape[0], 1)
            if len(p.shape) == 1:
                p = p.reshape(p.shape[0], 1)
            self.sum_metric += ((l - p) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _to_numpy(label)
            p = _to_numpy(pred)
            if len(l.shape) == 1:
                l = l.reshape(l.shape[0], 1)
            if len(p.shape) == 1:
                p = p.reshape(p.shape[0], 1)
            self.sum_metric += _np.sqrt(((l - p) ** 2.0).mean())
            self.num_inst += 1


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _to_numpy(label).ravel()
            p = _to_numpy(pred)
            assert l.shape[0] == p.shape[0]
            prob = p[_np.arange(l.shape[0]), _np.int64(l)]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += l.shape[0]


@register
@alias("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    update = CrossEntropy.update


@register
@alias("pearson_correlation")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _to_numpy(label).ravel()
            p = _to_numpy(pred).ravel()
            self.sum_metric += _np.corrcoef(p, l)[0, 1]
            self.num_inst += 1


class Perplexity(EvalMetric):
    """reference: metric.py Perplexity (exp of per-token CE)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            l = _to_numpy(label).astype("int64").ravel()
            p = _to_numpy(pred)
            p = p.reshape(-1, p.shape[-1])
            probs = p[_np.arange(l.shape[0]), l]
            if self.ignore_label is not None:
                ignore = (l == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.log(_np.maximum(1e-10, probs)).sum()
            num += l.shape[0]
        self.sum_metric += _np.exp(loss / num) if num > 0 else 0.0
        self.num_inst += 1


_REG.register(Perplexity, "perplexity")


@register
@alias("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = _to_numpy(pred).sum()
            self.sum_metric += loss
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            l = _to_numpy(label)
            p = _to_numpy(pred)
            reval = self._feval(l, p)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (reference: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
