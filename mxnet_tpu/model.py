"""Checkpoint helpers (reference: python/mxnet/model.py
save_checkpoint/load_checkpoint; the legacy FeedForward API is covered
by Module).

Both helpers are thin shims over the checkpoint & recovery subsystem
(``mxnet_tpu.checkpoint``): saves are atomic (temp + fsync + rename)
with a sidecar checksum manifest, and loads verify the manifest so a
torn ``.params`` file raises a clear error instead of silently feeding
half-written weights into a run (docs/CHECKPOINTING.md).
"""

from __future__ import annotations

from . import checkpoint as _checkpoint

BatchEndParam = None  # kept in module.base_module


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save symbol JSON + params (reference: model.py save_checkpoint).

    Shim over :func:`mxnet_tpu.checkpoint.save_legacy` — same file
    layout as the reference, written atomically with checksums."""
    _checkpoint.save_legacy(prefix, epoch, symbol, arg_params, aux_params)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params)
    (reference: model.py load_checkpoint).

    Shim over :func:`mxnet_tpu.checkpoint.load_legacy` — verifies the
    sidecar manifest's checksums when present."""
    return _checkpoint.load_legacy(prefix, epoch)


class FeedForward:
    """Legacy estimator-style trainer (reference: model.py FeedForward,
    deprecated upstream in favor of Module).

    Implemented as a thin adapter over :class:`mxnet_tpu.module.Module`
    — the reference's own migration advice — so era scripts written
    against ``mx.model.FeedForward(...)`` keep running.  Accepts numpy
    arrays, NDArrays, or DataIters for X/y.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        import warnings

        warnings.warn("mxnet.model.FeedForward is deprecated; use "
                      "mxnet.mod.Module instead", DeprecationWarning,
                      stacklevel=2)
        from .context import cpu
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else \
            [ctx] if ctx is not None else [cpu()]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = dict(arg_params) if arg_params else None
        self.aux_params = dict(aux_params) if aux_params else None
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # ------------------------------------------------------------ plumbing
    def _as_iter(self, X, y=None, shuffle=False):
        from .io.io import DataIter, NDArrayIter
        from .ndarray import NDArray

        if isinstance(X, DataIter):
            return X
        data = X.asnumpy() if isinstance(X, NDArray) else X
        label = y.asnumpy() if isinstance(y, NDArray) else y
        batch = min(self.numpy_batch_size, len(data))
        return NDArrayIter(data=data, label=label, batch_size=batch,
                           shuffle=shuffle)

    def _bind(self, it, for_training):
        from .module.module import Module

        if self._module is None:
            self._module = Module(self.symbol, context=self.ctx)
        mod = self._module
        shapes = [tuple(d.shape) for d in it.provide_data]
        signature = (for_training, shapes)
        if getattr(self, "_bind_signature", None) != signature:
            # keep learned params across rebinds (predict after fit,
            # new batch size, train after predict)
            if mod.binded and mod.params_initialized:
                self.arg_params, self.aux_params = mod.get_params()
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label if for_training else None,
                     for_training=for_training, force_rebind=True)
            self._bind_signature = signature
            if self.allow_extra_params and self.arg_params:
                names = set(self.symbol.list_arguments())
                self.arg_params = {k: v for k, v in self.arg_params.items()
                                   if k in names}
            mod.init_params(initializer=self.initializer,
                            arg_params=self.arg_params,
                            aux_params=self.aux_params,
                            allow_missing=self.arg_params is not None)
        return mod

    # ------------------------------------------------------------- training
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        if self.num_epoch is None:
            raise ValueError("FeedForward.fit: num_epoch was not set "
                             "(reference requires it)")
        if logger is not None:
            import logging as _logging

            logger.setLevel(getattr(logger, "level", _logging.INFO))
        train = self._as_iter(X, y, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._as_iter(eval_data[0], eval_data[1])
        mod = self._bind(train, for_training=True)
        opt_kwargs = dict(self.kwargs)
        mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=opt_kwargs,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        import numpy as _np

        it = self._as_iter(X)
        if reset:
            it.reset()
        mod = self._bind(it, for_training=False)
        outs, datas, labels = [], [], []
        for i, batch in enumerate(it):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            n = batch.data[0].shape[0] - batch.pad
            outs.append(mod.get_outputs()[0].asnumpy()[:n])
            if return_data:
                datas.append(batch.data[0].asnumpy()[:n])
                if batch.label:
                    labels.append(batch.label[0].asnumpy()[:n])
        out = _np.concatenate(outs) if outs else _np.empty((0,))
        if return_data:
            return (out, _np.concatenate(datas),
                    _np.concatenate(labels) if labels else None)
        return out

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        from . import metric as metric_mod

        it = self._as_iter(X)
        if reset:
            it.reset()
        mod = self._bind(it, for_training=False)
        metric = metric_mod.create(eval_metric)
        mod.score(it, metric, num_batch=num_batch)
        return metric.get()[1]

    # ----------------------------------------------------------- checkpoint
    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        save_checkpoint(prefix, epoch or 0, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a new model from scratch (reference: FeedForward.create)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
