"""Checkpoint helpers (reference: python/mxnet/model.py
save_checkpoint/load_checkpoint; the legacy FeedForward API is covered
by Module).
"""

from __future__ import annotations

from .ndarray import load as nd_load, save as nd_save
from .symbol import load as sym_load

BatchEndParam = None  # kept in module.base_module


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save symbol JSON + params (reference: model.py save_checkpoint)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params)
    (reference: model.py load_checkpoint)."""
    symbol = sym_load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)
