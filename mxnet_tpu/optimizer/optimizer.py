"""Optimizers (reference: python/mxnet/optimizer/optimizer.py:46-1608).

Same registry/API surface: ``Optimizer.create_optimizer``/``create``,
per-param lr/wd multipliers, ``create_state``, ``update``, and the
``Updater`` used by KVStore.  Updates dispatch to the fused update ops
(ops/optimizer_ops.py) so each step is one XLA kernel per weight; the
reference's multi-tensor aggregation (MXNET_OPTIMIZER_AGGREGATION_SIZE)
is unnecessary under jit — XLA fuses across weights when the whole step
is staged (gluon Trainer.step_fused / Module) — but the eager path here
still keeps per-weight fused kernels.
"""

from __future__ import annotations

import math
import pickle
import threading

import numpy as _np

from ..base import MXNetError, Registry
from ..ndarray import NDArray, imperative_invoke, zeros

_REG = Registry("optimizer")


# ------------------------------------------------------------------ compiled-step scalar feed
# Active while compiled_step.py traces an optimizer update into the
# whole-step XLA program.  Per-step host scalars (scheduler lr,
# bias-correction terms, the step count t) must not be baked into the
# trace as constants — the feed supplies a traced stand-in per
# (param index, scalar name) slot, and the CompiledStep recomputes the
# concrete values host-side every step (via Optimizer.step_scalars)
# and passes them into the jitted program as arguments.  The fused
# update kernels already declare these names in traced_attrs, so the
# tracer values flow straight through the per-op jit cache without
# becoming cache-key components.
_SCALAR_FEED = threading.local()


class scalar_feed:
    """Scope mapping ``(param index, scalar name) -> traced value`` for
    the duration of a compiled-step trace (compiled_step.py)."""

    def __init__(self, table):
        self.table = table

    def __enter__(self):
        stack = getattr(_SCALAR_FEED, "stack", None)
        if stack is None:
            stack = _SCALAR_FEED.stack = []
        stack.append(self.table)
        return self

    def __exit__(self, *a):
        _SCALAR_FEED.stack.pop()


def _fed(index, name):
    """The traced stand-in for slot ``(index, name)``, or None when no
    feed is active (the eager path: zero cost beyond one getattr)."""
    stack = getattr(_SCALAR_FEED, "stack", None)
    if not stack:
        return None
    return stack[-1].get((index, name))


def feed_active():
    """True while a compiled-step trace is feeding optimizer scalars."""
    return bool(getattr(_SCALAR_FEED, "stack", None))


def register(klass):
    _REG.register(klass)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.create(name, **kwargs)


class Optimizer:
    """Base optimizer (reference: optimizer.py:46)."""

    # True when update() reads its per-step scalars only through the
    # feed-aware accessors below (_get_lr/_get_wd/_t or an overridden
    # step_scalars) — the contract compiled_step.py needs to trace the
    # update into a whole-step XLA program without baking per-step
    # values in.  Optimizers with host-side cross-step recurrences
    # (Nadam's m_schedule), host syncs (LBSGD's norm fetch), or raw
    # NDArray-math on host scalars stay False and keep the eager path.
    compiled_step_safe = False

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        self.param_dict = param_dict if param_dict else {}

    create_optimizer = staticmethod(create)

    # ------------------------------------------------------------- state
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for low-precision weights
        (reference: optimizer.py create_state_multi_precision)."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype in (_np.float16,):
            weight_master_copy = weight.astype(_np.float32)
            return (weight_master_copy, self.create_state(index, weight_master_copy))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (_np.float16,):
            master, base_state = state
            grad32 = grad.astype(_np.float32)
            self.update(index, master, grad32, base_state)
            weight[:] = master.astype(weight.dtype)
        else:
            self.update(index, weight, grad, state)

    # ------------------------------------------------------------- lr/wd
    @property
    def learning_rate(self):
        """Current lr — scheduler value when one is set (reference:
        optimizer.py Optimizer.learning_rate)."""
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("LRScheduler of the optimizer has already been defined")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if feed_active():
            # compiled-step trace: the CompiledStep advances the host
            # counters itself (once per real step); the one-time trace
            # must not double-advance them
            return
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lr(self, index):
        fed = _fed(index, "lr")
        if fed is not None:
            return fed
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        fed = _fed(index, "wd")
        if fed is not None:
            return fed
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _t(self, index):
        """The step count update() derives bias corrections from —
        the already-advanced per-index count on the eager path, the
        feed's traced ``t`` under a compiled-step trace."""
        fed = _fed(index, "t")
        if fed is not None:
            return fed
        return self._index_update_count[index]

    def _t_host(self, index):
        """Host-side per-index step count for ``step_scalars``; 1
        before the first update (CompiledStep probes step_scalars once
        at build time purely for the slot NAMES — the values are
        refilled after every real count advance)."""
        return max(1, self._index_update_count.get(index, 0))

    def step_scalars(self, index):
        """Per-step scalars this optimizer's ``update()`` reads for
        ``index`` — the compiled-step protocol: ``CompiledStep``
        recomputes this dict host-side every step (after advancing the
        update counts) and feeds the values into the jitted whole-step
        program as traced arguments, one slot per (index, name).
        Keys must match the names ``update()`` reads through the
        feed-aware accessors (``lr``/``wd``/``t`` here; subclasses
        with extra per-step scalars extend the dict)."""
        return {"lr": self._get_lr(index), "wd": self._get_wd(index)}

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("lr_scheduler", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.lr_scheduler = None


def _fused(name, index, weight, grad, states, opt, **extra):
    """Run a fused update op and write results back in place.

    Per-step scalars (scheduler lr, bias-correction t, ...) are declared
    ``traced_attrs`` on the kernels, so the registry feeds them to the
    compiled update as weak-typed traced arguments — steady-state steps
    never recompile, and bf16/fp16 weights are not promoted.

    A row_sparse gradient with opt.lazy_update routes to the
    `_sparse_<name>` lazy kernel (reference: optimizer_op.cc FComputeEx
    storage dispatch) — only the gradient's rows are touched."""
    attrs = {"lr": opt._get_lr(index),
             "wd": opt._get_wd(index),
             "rescale_grad": opt.rescale_grad,
             "clip_gradient": opt.clip_gradient if opt.clip_gradient else -1.0}
    attrs.update(extra)
    name, inputs = _route_sparse(name, weight, grad, states,
                                 getattr(opt, "lazy_update", False))
    outs = imperative_invoke(name, inputs, attrs)
    weight._assign(outs[0]._data)
    for st, new in zip(states, outs[1:]):
        st._assign(new._data)


def _route_sparse(name, weight, grad, states, lazy):
    """Storage dispatch shared by every fused update call site
    (reference: optimizer_op.cc FComputeEx selection)."""
    if getattr(grad, "stype", "default") == "row_sparse" and lazy:
        return "_sparse_" + name, [weight, grad.data, grad.indices] + \
            list(states)
    return name, [weight, grad] + list(states)


@register
class SGD(Optimizer):
    """SGD with momentum + optional multi-precision
    (reference: optimizer.py SGD; fused kernels optimizer_op.cc)."""

    compiled_step_safe = True

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        if state is None:
            _fused("sgd_update", index, weight, grad, [], self)
        else:
            _fused("sgd_mom_update", index, weight, grad, [state], self,
                   momentum=self.momentum)


@register
class Test(Optimizer):
    """Trivial test optimizer (reference: optimizer.py Test)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        """w += rescale_grad * grad (reference: optimizer.py:1600)."""
        weight[:] = weight + grad * self.rescale_grad
        state[:] = weight


ccSGD = register(type("ccSGD", (SGD,), {}))  # deprecated alias (reference parity)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptation
    (reference: optimizer.py LBSGD)."""

    # update() host-syncs (weight/grad norm fetch) — eager only
    compiled_step_safe = False

    def __init__(self, warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(**kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.adaptive = True

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        # LARS trust ratio
        wnorm = float(weight.norm().asscalar())
        gnorm = float(grad.norm().asscalar()) * self.rescale_grad
        if wnorm > 0 and gnorm > 0:
            lr = lr * min(wnorm / (gnorm + wd * wnorm + 1e-9), 10.0)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        if state is not None:
            state[:] = self.momentum * state - lr * g
            weight[:] = weight + state
        else:
            weight[:] = weight - lr * g


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = g + self.lamda * g * g * (weight - prev)
        if mom is not None:
            mom[:] = self.momentum * mom - lr * (comp + wd * weight)
            step = mom
        else:
            step = -lr * (comp + wd * weight)
        prev[:] = weight
        weight[:] = weight + step


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: optimizer.py NAG)."""

    compiled_step_safe = True

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        if state is None:
            _fused("sgd_update", index, weight, grad, [], self)
        else:
            _fused("nag_mom_update", index, weight, grad, [state], self,
                   momentum=self.momentum)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        from ..ndarray import random as ndr

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        noise = ndr.normal(0, math.sqrt(lr), shape=weight.shape)
        weight[:] = weight - lr / 2 * (g + wd * weight) + noise


@register
class Adam(Optimizer):
    """reference: optimizer.py Adam; fused adam_update kernel."""

    compiled_step_safe = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def _bc_lr(self, index):
        """Bias-corrected per-step lr.  Computed host-side in double
        precision (the reference semantics); under a compiled-step
        trace the feed supplies the traced stand-in and the SAME host
        math runs in step_scalars each step — eager and compiled runs
        see bit-identical scalar values."""
        fed = _fed(index, "lr")
        if fed is not None:
            return fed
        t = self._t_host(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        return self._get_lr(index) * math.sqrt(coef2) / coef1

    def step_scalars(self, index):
        return {"lr": self._bc_lr(index), "wd": self._get_wd(index)}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        # bias-corrected lr varies EVERY step → traced input, not attr
        # (a static attr would recompile the kernel each step)
        lr = self._bc_lr(index)
        if isinstance(lr, (int, float)):
            lr = float(lr)
        mean, var = state
        attrs = {"wd": self._get_wd(index),
                 "rescale_grad": self.rescale_grad,
                 "clip_gradient": self.clip_gradient if self.clip_gradient else -1.0,
                 "beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon}
        opname, inputs = _route_sparse("adam_update", weight, grad,
                                       [mean, var], self.lazy_update)
        outs = imperative_invoke(opname, inputs + [lr], attrs)
        weight._assign(outs[0]._data)
        mean._assign(outs[1]._data)
        var._assign(outs[2]._data)


@register
class Signum(Optimizer):
    """reference: optimizer.py Signum (signSGD + momentum)."""

    compiled_step_safe = True

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        if state is None:
            _fused("signsgd_update", index, weight, grad, [], self)
        else:
            _fused("signum_update", index, weight, grad, [state], self,
                   momentum=self.momentum, wd_lh=self.wd_lh)


@register
class FTML(Optimizer):
    """reference: optimizer.py FTML."""

    compiled_step_safe = True

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999, epsilon=1e-8,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def step_scalars(self, index):
        return {"lr": self._get_lr(index), "wd": self._get_wd(index),
                "t": float(self._t_host(index))}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        d, v, z = state
        attrs = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                 "rescale_grad": self.rescale_grad,
                 "clip_grad": self.clip_gradient if self.clip_gradient else -1.0,
                 "beta1": self.beta1, "beta2": self.beta2,
                 "epsilon": self.epsilon, "t": t}
        outs = imperative_invoke("ftml_update", [weight, grad, d, v, z], attrs)
        weight._assign(outs[0]._data)
        d._assign(outs[1]._data)
        v._assign(outs[2]._data)
        z._assign(outs[3]._data)


@register
class Ftrl(Optimizer):
    """reference: optimizer.py Ftrl."""

    compiled_step_safe = True

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        attrs = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                 "rescale_grad": self.rescale_grad,
                 "clip_gradient": self.clip_gradient if self.clip_gradient else -1.0,
                 "lamda1": self.lamda1, "beta": self.beta}
        outs = imperative_invoke("ftrl_update", [weight, grad, z, n], attrs)
        weight._assign(outs[0]._data)
        z._assign(outs[1]._data)
        n._assign(outs[2]._data)


@register
class Adamax(Optimizer):
    """reference: optimizer.py Adamax."""

    compiled_step_safe = True

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def step_scalars(self, index):
        return {"lr": self._get_lr(index), "wd": self._get_wd(index),
                "t": float(self._t_host(index))}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        m, u = state
        _fused("adamax_update", index, weight, grad, [m, u], self,
               beta1=self.beta1, beta2=self.beta2, t=self._t(index))


@register
class Nadam(Optimizer):
    """reference: optimizer.py Nadam."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        # the cross-step schedule product stays host-tracked in float64
        self.m_schedule = self.m_schedule * momentum_t
        m, v = state
        _fused("nadam_update", index, weight, grad, [m, v], self,
               beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
               t=t, m_schedule=self.m_schedule, momentum_t=momentum_t,
               momentum_t_1=momentum_t_1)


@register
class AdaGrad(Optimizer):
    """reference: optimizer.py AdaGrad."""

    # dense path runs the fused adagrad_update kernel reading lr/wd
    # through the feed-aware accessors — traceable into the whole-step
    # program (the row_sparse branch never triggers under a trace:
    # traced grads are dense)
    compiled_step_safe = True

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if getattr(grad, "stype", "default") == "row_sparse":
            # touch only the gradient's rows (reference
            # _sparse_adagrad_update)
            attrs = {"lr": lr, "wd": wd, "epsilon": self.float_stable_eps,
                     "rescale_grad": self.rescale_grad,
                     "clip_gradient": self.clip_gradient
                     if self.clip_gradient else -1.0}
            outs = imperative_invoke(
                "_sparse_adagrad_update",
                [weight, grad.data, grad.indices, state], attrs)
            weight._assign(outs[0]._data)
            state._assign(outs[1]._data)
            return
        _fused("adagrad_update", index, weight, grad, [state], self,
               epsilon=self.float_stable_eps)


@register
class RMSProp(Optimizer):
    """reference: optimizer.py RMSProp (Tieleman & Hinton; centered variant)."""

    compiled_step_safe = True

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                    zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                    zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        if self.centered:
            n, g_st, delta = state
            attrs = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                     "rescale_grad": self.rescale_grad,
                     "clip_gradient": self.clip_gradient if self.clip_gradient else -1.0,
                     "gamma1": self.gamma1, "gamma2": self.gamma2,
                     "epsilon": self.epsilon}
            outs = imperative_invoke("rmspropalex_update",
                                     [weight, grad, n, g_st, delta], attrs)
            weight._assign(outs[0]._data)
            n._assign(outs[1]._data)
            g_st._assign(outs[2]._data)
            delta._assign(outs[3]._data)
        else:
            _fused("rmsprop_update", index, weight, grad, [state[0]], self,
                   gamma1=self.gamma1, epsilon=self.epsilon,
                   clip_weights=self.clip_weights if self.clip_weights else -1.0)


@register
class AdaDelta(Optimizer):
    """reference: optimizer.py AdaDelta."""

    # fused adadelta_update kernel, wd via the feed-aware accessor, no
    # lr in the step math — traceable into the whole-step program
    compiled_step_safe = True

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        acc_g, acc_delta = state
        _fused("adadelta_update", index, weight, grad, [acc_g, acc_delta],
               self, rho=self.rho, epsilon=self.epsilon)


class Updater:
    """KVStore-side updater (reference: optimizer.py:1608 get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = False

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer) if dump_optimizer
                            else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
