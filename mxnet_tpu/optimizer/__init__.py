"""``mx.optimizer`` (reference: python/mxnet/optimizer/)."""

from .optimizer import (SGD, Adam, AdaDelta, AdaGrad, Adamax, DCASGD, FTML,  # noqa: F401
                        Ftrl, LBSGD, NAG, Nadam, Optimizer, RMSProp, SGLD,
                        Signum, Test, Updater, ccSGD, create, get_updater,
                        register)
