"""Checkpoint & recovery subsystem — atomic async checkpointing and
one-call auto-resume.

The observability cycle (runtime telemetry, memory/cost analytics, the
numerics health layer) made training runs self-observing; this module
makes them *survivable*.  Every legacy persistence path in the
reference wrote in place, blocking, and non-atomically
(``model.py:save_checkpoint``, ``Block.save_parameters``,
``Trainer.save_states``) — a preempted TPU worker or a SIGKILL
mid-write loses the run.  Here all of them route through one
crash-consistent substrate:

- :func:`atomic_write` — temp file in the target directory + flush +
  ``os.fsync`` + ``os.replace`` (+ directory fsync), so no persistence
  path can leave a torn file under its final name.
- :class:`CheckpointManager` — directory-per-checkpoint layout with a
  ``MANIFEST.json`` commit record carrying per-file SHA-256 checksums.
  A checkpoint exists iff its manifest is valid and every checksum
  matches; :meth:`CheckpointManager.latest` skips torn or corrupt
  checkpoints (warning through ``log.py``) and falls back to the
  newest fully-valid one.  Keep-last-N retention prunes committed
  checkpoints beyond ``keep`` plus any stale temp directories.
- **Asynchronous snapshots.**  XLA device buffers are immutable and
  every in-place NDArray write *rebinds* the buffer
  (``NDArray._assign``), so capturing the current ``_data`` references
  under the training thread is a **zero-copy, sync-free, consistent
  device-side snapshot** — the optimizer stepping afterwards creates
  new buffers and never mutates captured ones.  Host materialization
  and disk I/O happen on a background writer thread; the one batched
  ``jax.device_get`` there (:func:`_materialize`) is the module's
  single deliberate host-sync sink, pragma'd per the callgraph rule
  exactly like ``health._fetch``.  Back-to-back saves coalesce: while
  one snapshot is being written, only the newest queued snapshot
  survives (counted in ``totals['coalesced']``).
- **Complete resumable unit.**  One manifest covers parameters,
  optimizer/Trainer updater state (device buffers captured the same
  zero-copy way), the stripped optimizer hyper-state (update counters,
  schedulers — never ``param_dict``), the framework RNG state
  (seed + counter), the step clock, and a ``runtime_stats``
  health/flight probe.  :meth:`CheckpointManager.restore` (or
  module-level :func:`auto_resume`) puts all of it back in one call.

Cost model (pinned by ``tests/test_bench_gate.py``): disabled — the
default — the :func:`on_step` hook inside ``gluon.Trainer.step`` costs
one dict read and nothing else.  Enabled, a sampled step pays reference
captures plus a pickle of host-side scalars; the device and the
training thread never block on disk.

Environment variables (docs/ENV_VARS.md, docs/CHECKPOINTING.md)
---------------------------------------------------------------
``MXNET_TPU_CKPT``            checkpoint directory: enable the global
    manager at import (auto-save from ``Trainer.step``).
``MXNET_TPU_CKPT_INTERVAL``   save every N trainer steps (default 100).
``MXNET_TPU_CKPT_KEEP``       keep-last-N retention (default 5).
``MXNET_TPU_CKPT_ASYNC``      ``0`` forces blocking (synchronous)
    writes (default 1: background writer thread).

Security note: checkpoint payloads (``trainer.pkl``) are plain pickle,
like the reference's ``Trainer.save_states`` — load checkpoints only
from directories you trust, same trust model as the reference.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import re
import shutil
import threading
import time

from . import histogram as _histogram
from . import runtime_stats as _rts
from . import stepstats as _stepstats
from .log import get_logger, warn_rate_limited

__all__ = ["atomic_write", "CheckpointManager", "enable", "disable",
           "is_enabled", "manager", "on_step", "auto_resume", "lineage",
           "save_legacy", "load_legacy", "load_aux", "MANIFEST_NAME",
           "TRAINER_STATES_MAGIC", "TRAINER_STATES_VERSION"]

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_VERSION = 1

# Trainer.save_states header (gluon/trainer.py): magic + u8 version +
# newline, then the pickle payload.  Legacy headerless files still load.
TRAINER_STATES_MAGIC = b"MXTPUTRAINER"
TRAINER_STATES_VERSION = 1

_state = {"on": False}
_GLOBAL: list = []              # [CheckpointManager] while enabled

_logger_cache: list = []
_tmp_seq = iter(range(1, 1 << 62))


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.checkpoint"))
    return _logger_cache[0]


# ------------------------------------------------------------ atomic IO


@contextlib.contextmanager
def atomic_write(path):
    """Yield a temp path in ``path``'s directory; on clean exit fsync it
    and ``os.replace`` onto ``path`` (then fsync the directory), so the
    final name only ever holds a complete file.  On error the temp file
    is removed and nothing under ``path`` changes.

    THE atomic-write primitive every persistence path routes through
    (``Block.save_parameters``, ``Trainer.save_states``,
    ``model.save_checkpoint``, the manager's data files + manifest).
    """
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    tmp = os.path.join(d, ".%s.%d.%d.tmp" % (os.path.basename(path),
                                             os.getpid(), next(_tmp_seq)))
    try:
        yield tmp
        _fsync_file(tmp)
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    # directory fsync makes the rename itself durable; some platforms
    # (or exotic filesystems) refuse O_RDONLY on dirs — best effort
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return h.hexdigest()
            h.update(b)


# ------------------------------------------------- device-side capture


class _NDLeaf:
    """Marker for an NDArray leaf inside a captured/serialized state
    tree: holds the immutable device buffer at capture time and the
    materialized numpy value after the background write.  Restoring
    turns it back into an NDArray."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __reduce__(self):
        return (_NDLeaf, (self.value,))


def _capture_tree(obj):
    """Zero-copy capture: NDArray leaves become :class:`_NDLeaf` refs to
    their current (immutable) device buffer; containers are rebuilt so
    later mutation of the live tree cannot touch the snapshot; host
    scalars pass through.  Never syncs."""
    from .ndarray import NDArray

    if isinstance(obj, NDArray):
        return _NDLeaf(obj._data)
    if isinstance(obj, dict):
        return {k: _capture_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_capture_tree(v) for v in obj)
    return obj


def _tree_leaves(obj, out):
    if isinstance(obj, _NDLeaf):
        out.append(obj)
    elif isinstance(obj, dict):
        for v in obj.values():
            _tree_leaves(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _tree_leaves(v, out)
    return out


def _restore_tree(obj, ctx=None):
    """Inverse of capture after a round trip: _NDLeaf(numpy) → NDArray."""
    from .ndarray import array

    if isinstance(obj, _NDLeaf):
        return array(obj.value, ctx=ctx)
    if isinstance(obj, dict):
        return {k: _restore_tree(v, ctx) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_restore_tree(v, ctx) for v in obj)
    return obj


def _materialize(snapshot):
    """Bring every captured device buffer in a snapshot to host, in ONE
    batched transfer, replacing each :class:`_NDLeaf`'s buffer with its
    numpy value in place.

    THE deliberate host-sync sink of the checkpoint layer: it runs only
    on the background writer thread (or inside an explicitly blocking
    ``save``), never on a compute path — the training step queues
    buffer references and moves on."""
    import jax
    import numpy as np

    leaves = []
    _tree_leaves(snapshot.get("params", {}), leaves)
    _tree_leaves(snapshot.get("trainer", {}), leaves)
    if not leaves:
        return snapshot
    host = jax.device_get([lf.value for lf in leaves])  # mxlint: disable=trace-host-sync
    for lf, hv in zip(leaves, host):
        lf.value = np.asarray(hv)
    return snapshot


def _strip_optimizer(optimizer):
    """Pickle an Optimizer's hyper-state without ``param_dict`` (live
    Parameters — pickling them would materialize full weight tensors on
    the training thread; the per-index multipliers are folded into
    lr_mult/wd_mult exactly like the dist kvstore wire copy)."""
    import copy

    wire = copy.copy(optimizer)
    wire.param_dict = {}
    wire.lr_mult = dict(optimizer.lr_mult)
    wire.wd_mult = dict(optimizer.wd_mult)
    for idx, p in getattr(optimizer, "param_dict", {}).items():
        if getattr(p, "lr_mult", 1.0) != 1.0:
            wire.lr_mult[idx] = p.lr_mult
        if getattr(p, "wd_mult", 1.0) != 1.0:
            wire.wd_mult[idx] = p.wd_mult
    return pickle.dumps(wire, protocol=pickle.HIGHEST_PROTOCOL)


# ------------------------------------------------------------- manager


class CheckpointManager:
    """Atomic, asynchronous, self-validating checkpoint store.

    Layout: ``<directory>/<prefix>-<step:08d>/`` holding ``params.npz``,
    ``trainer.pkl`` (when trainer state was captured), and the
    ``MANIFEST.json`` commit record.  The whole checkpoint is staged in
    a temp directory and renamed into place only after every file (and
    the manifest) is fsynced — a checkpoint either exists completely or
    not at all; :meth:`latest` additionally re-hashes every file so a
    corrupted-on-disk checkpoint is skipped, not half-loaded.
    """

    def __init__(self, directory, keep=5, interval=None, async_write=None,
                 prefix="ckpt"):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = max(1, int(keep))
        self.interval = int(interval) if interval else 0
        if async_write is None:
            async_write = os.environ.get("MXNET_TPU_CKPT_ASYNC", "1") != "0"
        self.async_write = bool(async_write)
        self.prefix = prefix
        self._final_re = re.compile(
            r"^%s-(\d{8,})$" % re.escape(prefix))
        self.step_clock = 0
        self.last_good = None       # {"path", "step"} of newest commit
        self.last_error = None
        # best-effort monitoring counters; each key has exactly one
        # writer (trainer thread: saves/coalesced; writer thread:
        # written/errors/corrupt_skipped), so no lock is shared
        # mxlint: disable=thread-shared-state -- single writer per key
        self.totals = {"saves": 0, "written": 0, "coalesced": 0,
                       "corrupt_skipped": 0, "errors": 0}
        self._cv = threading.Condition()
        self._queued = None         # newest pending snapshot
        self._writing = False
        self._stop = False
        self._thread = None
        self._prune_stale_tmp()

    # ------------------------------------------------------------ save
    def save_trainer(self, trainer, step=None, extra=None, pin=False):
        """Snapshot a ``gluon.Trainer``'s complete resumable unit —
        parameters, updater state, optimizer hyper-state, RNG, step —
        without blocking: device buffers are captured by reference
        (immutable under XLA; in-place writes rebind), everything else
        is host scalars.  Returns immediately in async mode.

        ``pin=True`` materializes the captured buffers to host BEFORE
        returning (one batched transfer on the calling thread): the
        compiled whole-step path (compiled_step.py) DONATES the param /
        optimizer buffers into its next program call, which would
        invalidate by-reference captures before the background writer
        reads them — pinning trades one bounded sync per checkpoint
        interval for a snapshot donation cannot corrupt.  Pinning also
        engages AUTOMATICALLY once any CompiledStep has stepped in this
        process (``compiled_step.donation_active``), so a manual
        ``save_trainer`` or a mixed eager/compiled loop can never hand
        the writer buffers a later compiled step deletes."""
        from . import compiled_step as _compiled
        from . import random as _random

        pin = pin or _compiled.donation_active()

        step = self.step_clock if step is None else int(step)
        params = {}
        for p in trainer._params:
            data = p._data
            if data is None:
                continue
            params[p.name] = _NDLeaf(p.list_data()[0]._data)
        updater = trainer._updaters[0] if trainer._updaters else None
        trainer_state = {}
        if updater is not None:
            trainer_state["states"] = _capture_tree(updater.states)
            trainer_state["optimizer"] = _strip_optimizer(
                trainer._optimizer)
        snapshot = {"step": step, "params": params,
                    "trainer": trainer_state,
                    "rng": dict(_random.get_state()),
                    "extra": extra}
        if pin:
            _materialize(snapshot)
        return self._submit(snapshot)

    def save(self, step, params, extra=None, aux=None):
        """Snapshot a plain ``{name: NDArray}`` mapping (no trainer).

        ``aux``, when given, is an opaque picklable sideband payload
        committed alongside the arrays (``aux.pkl``, checksummed in the
        manifest) and read back with :func:`load_aux` — the hook
        non-Trainer state owners (the dist parameter-server shards, the
        coming ZeRO per-rank shard files) persist their bookkeeping
        through, atomically with the data it describes."""
        caps = {k: _NDLeaf(getattr(v, "_data", v))
                for k, v in params.items()}
        from . import random as _random

        snapshot = {"step": int(step), "params": caps, "trainer": {},
                    "rng": dict(_random.get_state()), "extra": extra,
                    "aux": aux}
        return self._submit(snapshot)

    def save_sharded(self, step, shard_files, aux=None):
        """Commit a SHARDED checkpoint: per-rank payload files under ONE
        global manifest (the ZeRO weight-update-sharding persistence
        path — each rank writes only the 1/n of params + optimizer
        state it owns, so checkpoint I/O shrinks with the data).

        ``shard_files`` maps file stem → picklable payload for the
        ranks THIS process owns.  Every process stages into the same
        deterministic directory (``<final>.tmp-shared`` — covered by
        the stale-tmp prune on crash), fsyncs its own files, then joins
        a ``host_allreduce`` barrier; process 0 ALONE then checksums
        everything staged, writes the single manifest (shard filenames
        in ``shard_files``, per-file SHA-256 in ``files`` so
        :meth:`verify`/:meth:`latest` gain corruption detection for
        free) and performs the atomic rename — the rank-0 commit
        barrier.  A SIGKILL anywhere before that rename leaves only a
        staging dir the next manager init removes; the previous valid
        checkpoint is untouched.  Synchronous by design: shard payloads
        are already host numpy (1/n sized), and the commit barrier must
        not race the next step's donation."""
        import jax
        import numpy as np

        from . import random as _random
        from .parallel.mesh import host_allreduce

        t0 = time.perf_counter()
        step = int(step)
        final = os.path.join(self.directory,
                             "%s-%08d" % (self.prefix, step))
        tmp = final + ".tmp-shared"
        proc0 = jax.process_index() == 0
        if proc0:
            # a stale staging dir from a crashed attempt would leak its
            # files into this manifest (the listdir below) — clear it
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
        host_allreduce(1.0)     # staging dir exists and is clean
        try:
            for name, payload in shard_files.items():
                fpath = os.path.join(tmp, name + ".pkl")
                with open(fpath, "wb") as f:
                    pickle.dump(payload, f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    os.fsync(f.fileno())
            host_allreduce(1.0)  # every rank's shard files are durable
            if not proc0:
                return final
            files = {}
            shard_names = sorted(os.listdir(tmp))
            for name in shard_names:
                fpath = os.path.join(tmp, name)
                files[name] = {"sha256": _sha256(fpath),
                               "bytes": os.path.getsize(fpath)}
            # empty params.npz keeps whole-checkpoint readers
            # (load_params, external tools) working unchanged
            ppath = os.path.join(tmp, "params.npz")
            with open(ppath, "wb") as f:
                np.savez(f)
                f.flush()
                os.fsync(f.fileno())
            files["params.npz"] = {"sha256": _sha256(ppath),
                                   "bytes": os.path.getsize(ppath)}
            if aux is not None:
                apath = os.path.join(tmp, "aux.pkl")
                with open(apath, "wb") as f:
                    pickle.dump(aux, f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    os.fsync(f.fileno())
                files["aux.pkl"] = {"sha256": _sha256(apath),
                                    "bytes": os.path.getsize(apath)}
            manifest = {"version": MANIFEST_VERSION, "step": step,
                        "time": time.time(), "pid": os.getpid(),
                        "files": files, "params": [],
                        "has_trainer": False,
                        "has_aux": aux is not None,
                        "shard_files": shard_names,
                        "rng": dict(_random.get_state()),
                        "probe": self._probe(), "extra": None,
                        "lineage": {"previous":
                                    self.last_good["path"]
                                    if self.last_good else None}}
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, default=repr)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            retired = None
            if os.path.isdir(final):
                retired = "%s.retire-%d-%d" % (final, os.getpid(),
                                               next(_tmp_seq))
                os.replace(final, retired)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
            if retired is not None:
                shutil.rmtree(retired, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.last_good = {"path": final, "step": step}
        self.totals["saves"] += 1
        self.totals["written"] += 1
        _rts.inc("checkpoint_saves")
        _rts.inc("checkpoint_writes")
        _rts.inc("checkpoint_sharded_saves")
        write_seconds = time.perf_counter() - t0
        _rts.inc("checkpoint_write_seconds", write_seconds)
        if _histogram._state["on"]:
            _histogram.observe("checkpoint:write", write_seconds)
        self._prune()
        return final

    def _submit(self, snapshot):
        snapshot["probe"] = self._probe()
        snapshot["time"] = time.time()
        self.totals["saves"] += 1
        _rts.inc("checkpoint_saves")
        if not self.async_write:
            self._write(snapshot)
            return None
        with self._cv:
            if self._queued is not None:
                # writer still busy with an older snapshot: only the
                # newest pending one survives (bounded memory — at most
                # two snapshots' buffers are ever pinned)
                self.totals["coalesced"] += 1
                _rts.inc("checkpoint_coalesced")
            self._queued = snapshot
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._writer_loop,
                    name="mxtpu-checkpoint-writer", daemon=True)
                self._thread.start()
            self._cv.notify_all()
        return None

    def _probe(self):
        """Lightweight runtime_stats/health marker for the manifest —
        counter dict reads only, never a drain, never a sync."""
        from . import health as _health

        probe = _rts.health_probe()
        hm = _health.monitor()
        if hm is not None:
            probe["health"] = {"step": hm.step,
                               "nan_steps": hm.totals["nan_steps"],
                               "inf_steps": hm.totals["inf_steps"],
                               "first_nan": dict(hm.first_nan)
                               if hm.first_nan else None}
        return probe

    # ---------------------------------------------------- writer thread
    def _writer_loop(self):
        while True:
            with self._cv:
                while self._queued is None and not self._stop:
                    self._cv.wait()
                if self._stop and self._queued is None:
                    return
                snapshot, self._queued = self._queued, None
                self._writing = True
            try:
                self._write(snapshot)
            except Exception as e:  # a failed write must not kill training
                self.last_error = "%s: %s" % (type(e).__name__, e)
                self.totals["errors"] += 1
                _rts.inc("checkpoint_errors")
                _logger().exception("async checkpoint write failed "
                                    "(step %s)", snapshot.get("step"))
            finally:
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()

    def wait(self, timeout=None):
        """Block until no snapshot is pending or being written (tests,
        clean shutdown).  Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._queued is not None or self._writing:
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                self._cv.wait(rem)
        return True

    def close(self):
        """Flush pending snapshots and stop the writer thread."""
        self.wait()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ------------------------------------------------------- the commit
    def _write(self, snapshot):
        import numpy as np

        t0 = time.perf_counter()
        _materialize(snapshot)
        step = snapshot["step"]
        final = os.path.join(self.directory,
                             "%s-%08d" % (self.prefix, step))
        tmp = "%s.tmp-%d-%d" % (final, os.getpid(), next(_tmp_seq))
        os.makedirs(tmp)
        try:
            files = {}
            params_np = {k: lf.value
                         for k, lf in snapshot["params"].items()}
            ppath = os.path.join(tmp, "params.npz")
            with open(ppath, "wb") as f:
                np.savez(f, **params_np)
                f.flush()
                os.fsync(f.fileno())
            files["params.npz"] = {"sha256": _sha256(ppath),
                                   "bytes": os.path.getsize(ppath)}
            if snapshot["trainer"]:
                tpath = os.path.join(tmp, "trainer.pkl")
                with open(tpath, "wb") as f:
                    pickle.dump(snapshot["trainer"], f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    os.fsync(f.fileno())
                files["trainer.pkl"] = {"sha256": _sha256(tpath),
                                        "bytes": os.path.getsize(tpath)}
            if snapshot.get("aux") is not None:
                apath = os.path.join(tmp, "aux.pkl")
                with open(apath, "wb") as f:
                    pickle.dump(snapshot["aux"], f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    os.fsync(f.fileno())
                files["aux.pkl"] = {"sha256": _sha256(apath),
                                    "bytes": os.path.getsize(apath)}
            manifest = {"version": MANIFEST_VERSION, "step": step,
                        "time": snapshot["time"], "pid": os.getpid(),
                        "files": files,
                        "params": sorted(snapshot["params"]),
                        "has_trainer": bool(snapshot["trainer"]),
                        "has_aux": snapshot.get("aux") is not None,
                        "rng": snapshot["rng"],
                        "probe": snapshot.get("probe"),
                        "extra": snapshot.get("extra"),
                        "lineage": {"previous":
                                    self.last_good["path"]
                                    if self.last_good else None}}
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, default=repr)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            retired = None
            if os.path.isdir(final):
                # same-step overwrite: move the old committed dir ASIDE
                # (not rmtree — a crash between delete and rename would
                # lose BOTH copies of this step) and delete it only
                # after the new commit has landed.  The ``.retire-``
                # name is NOT in the stale-tmp prune set: if we crash
                # here, manager init restores it to its final name.
                retired = "%s.retire-%d-%d" % (final, os.getpid(),
                                               next(_tmp_seq))
                os.replace(final, retired)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
            if retired is not None:
                shutil.rmtree(retired, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.last_good = {"path": final, "step": step}
        self.totals["written"] += 1
        _rts.inc("checkpoint_writes")
        write_seconds = time.perf_counter() - t0
        _rts.inc("checkpoint_write_seconds", write_seconds)
        if _histogram._state["on"]:
            # full commit wall-time (materialize + hash + fsync +
            # rename) — the tail of this distribution is what decides
            # whether async saves coalesce under a given interval
            _histogram.observe("checkpoint:write", write_seconds)
        self._prune()
        return final

    def _prune_stale_tmp(self):
        """Remove leftover staging dirs from crashed writes, and
        recover a ``.retire-`` dir (a committed checkpoint moved aside
        during a same-step overwrite) whose replacement never landed —
        that dir IS the only surviving copy of its step."""
        for name in os.listdir(self.directory):
            base, sep, _ = name.partition(".retire-")
            if sep and self._final_re.match(base):
                final = os.path.join(self.directory, base)
                path = os.path.join(self.directory, name)
                try:
                    if os.path.isdir(final):
                        shutil.rmtree(path, ignore_errors=True)
                    else:
                        os.replace(path, final)
                except OSError:
                    pass
                continue
            if ".tmp-" in name and self._final_re.match(
                    name.split(".tmp-")[0]):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def _prune(self):
        """Keep-last-N retention over committed checkpoints; stale temp
        staging dirs go too.  Torn final dirs (no valid manifest) older
        than the newest valid checkpoint are garbage from a previous
        crash and are removed, and quarantined ``.corrupt-*`` dirs are
        bounded to ``keep`` (newest kept for forensics) so recurring
        corruption cannot grow disk use without bound."""
        entries = self._scan()
        valid = [(s, p) for s, p, m in entries if m is not None]
        for step, path in valid[self.keep:]:
            shutil.rmtree(path, ignore_errors=True)
        if valid:
            newest = valid[0][0]
            for step, path, m in entries:
                if m is None and step < newest:
                    shutil.rmtree(path, ignore_errors=True)
        quarantined = sorted(
            n for n in os.listdir(self.directory)
            if ".corrupt-" in n
            and self._final_re.match(n.split(".corrupt-")[0]))
        for name in quarantined[:max(0, len(quarantined) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)
        self._prune_stale_tmp()

    # -------------------------------------------------------- read side
    def _scan(self):
        """[(step, path, manifest-or-None)] newest first; manifest is
        None when missing/unparseable (a torn checkpoint)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = self._final_re.match(name)
            if not m:
                continue
            path = os.path.join(self.directory, name)
            if not os.path.isdir(path):
                continue
            manifest = None
            try:
                with open(os.path.join(path, MANIFEST_NAME)) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                manifest = None
            out.append((int(m.group(1)), path, manifest))
        out.sort(key=lambda e: e[0], reverse=True)
        return out

    def verify(self, path, manifest=None):
        """Re-hash every file a manifest names; True iff the checkpoint
        is bit-for-bit what was committed."""
        if manifest is None:
            try:
                with open(os.path.join(path, MANIFEST_NAME)) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                return False
        try:
            for fname, meta in manifest.get("files", {}).items():
                fpath = os.path.join(path, fname)
                if os.path.getsize(fpath) != meta["bytes"] or \
                        _sha256(fpath) != meta["sha256"]:
                    return False
        except OSError:
            return False
        return True

    def latest(self):
        """The newest fully-valid checkpoint's manifest (with ``path``
        added), or None.  Torn checkpoints (no manifest — e.g. a
        SIGKILL mid-write) and corrupt ones (checksum mismatch) are
        skipped with a warning and QUARANTINED (renamed aside with a
        ``.corrupt`` marker, content kept for forensics) so every later
        scan neither re-hashes them nor re-counts the same corruption,
        falling back to the previous valid checkpoint."""
        for step, path, manifest in self._scan():
            if manifest is not None and self.verify(path, manifest):
                manifest = dict(manifest)
                manifest["path"] = path
                return manifest
            self.totals["corrupt_skipped"] += 1
            _rts.inc("checkpoint_corrupt_skipped")
            quarantine = "%s.corrupt-%d-%d" % (path, os.getpid(),
                                               next(_tmp_seq))
            try:
                os.replace(path, quarantine)
            except OSError:
                quarantine = path  # leave in place; next scan retries
            warn_rate_limited(
                _logger(), "checkpoint:corrupt:%s" % path, 60,
                "skipping torn/corrupt checkpoint %s (%s; quarantined "
                "as %s) — falling back to the previous valid "
                "checkpoint", path,
                "no valid manifest" if manifest is None
                else "checksum mismatch", quarantine)
        return None

    def load_params(self, manifest):
        """``{name: NDArray}`` from a checkpoint's params file."""
        import numpy as np

        from .ndarray import array

        with np.load(os.path.join(manifest["path"], "params.npz"),
                     allow_pickle=False) as data:
            return {k: array(data[k]) for k in data.files}

    def load_aux(self, manifest):
        """The opaque sideband payload saved via ``save(..., aux=)``,
        or None when the checkpoint carries none.  Plain pickle — same
        trust model as ``trainer.pkl`` (load only checkpoints from
        directories you trust)."""
        return load_aux(manifest)

    def load_shard_files(self, manifest):
        """``{rank: payload}`` from a sharded checkpoint's per-rank
        files (see :meth:`save_sharded`).  Rank indices are parsed from
        the ``<stem>-<rank>-of-<n>`` filename convention; checksums
        were already verified by ``latest()``/``verify`` before the
        manifest was handed out."""
        pat = re.compile(r"-(\d+)-of-(\d+)(?:\.pkl)?$")
        out = {}
        for name in manifest.get("shard_files", []):
            m = pat.search(name)
            if not m:
                continue
            with open(os.path.join(manifest["path"], name), "rb") as f:
                out[int(m.group(1))] = pickle.load(f)
        return out

    def restore(self, trainer=None, block=None, manifest=None):
        """One-call auto-resume: load the newest valid checkpoint back
        into a ``Trainer`` (parameters by name, updater state, optimizer
        hyper-state, RNG, step clock) and/or a Gluon ``block``
        (parameters via ``collect_params``).  Returns the manifest (with
        ``path`` and ``step``) or None when no valid checkpoint exists.
        """
        from . import random as _random

        from .base import MXNetError

        if manifest is None:
            # drain the writer first: a snapshot queued just before the
            # restore must be visible (and committed) before we decide
            # what "latest" is — otherwise it would land AFTER the
            # rollback and leave lineage pointing past the live state
            self.wait()
            manifest = self.latest()
        if manifest is None:
            return None
        params = self.load_params(manifest)
        targets = {}
        if trainer is not None:
            targets.update({p.name: p for p in trainer._params})
        if block is not None:
            targets.update(block.collect_params().items())
        matched = 0
        for name, value in params.items():
            p = targets.get(name)
            if p is not None and p._data is not None:
                p.set_data(value)
                matched += 1
        if targets and params and matched == 0:
            # a "successful" resume that restored nothing is the worst
            # failure mode: fresh weights with a restored step clock
            raise MXNetError(
                "checkpoint %s matched NONE of the %d target "
                "parameter(s) (checkpoint has %s...) — name/prefix "
                "mismatch or parameters not yet initialized (run one "
                "forward first)"
                % (manifest["path"], len(targets),
                   sorted(params)[:3]))
        if targets and matched < len(params):
            warn_rate_limited(
                _logger(), "checkpoint:partial:%s" % manifest["path"],
                60, "checkpoint %s: only %d of %d saved parameter(s) "
                "matched a target by name — the rest were NOT restored",
                manifest["path"], matched, len(params))
        missing = sorted(n for n in targets if n not in params)
        if missing:
            # the reverse gap is just as dangerous: a target param the
            # checkpoint never saw (e.g. a newly added layer) keeps its
            # fresh init while step/RNG/optimizer state are restored
            warn_rate_limited(
                _logger(), "checkpoint:missing:%s" % manifest["path"],
                60, "checkpoint %s does not cover %d target "
                "parameter(s) (%s...) — they keep their current "
                "(likely freshly initialized) values",
                manifest["path"], len(missing), missing[:3])
        if trainer is not None and manifest.get("has_trainer"):
            with open(os.path.join(manifest["path"], "trainer.pkl"),
                      "rb") as f:
                trainer_state = pickle.load(f)
            contexts = getattr(trainer, "_contexts", None) or []
            for i, u in enumerate(trainer._updaters):
                # fresh copy per updater, materialized on that
                # updater's device: per-device optimizer state must
                # never alias across updaters (trainer.py _update_impl
                # keeps one Updater per device copy) and must live next
                # to the weights it updates
                ctx = contexts[i] if i < len(contexts) else None
                states = _restore_tree(trainer_state.get("states", {}),
                                       ctx=ctx)
                u.states = states
                u.states_synced = dict.fromkeys(states, False)
            blob = trainer_state.get("optimizer")
            if blob is not None:
                src = pickle.loads(blob)
                hyper = dict(src.__dict__)
                hyper.pop("param_dict", None)
                trainer._optimizer.__dict__.update(hyper)
        rng = manifest.get("rng")
        if rng:
            _random.set_state(rng)
        self.step_clock = int(manifest.get("step", 0))
        self.last_good = {"path": manifest["path"],
                          "step": self.step_clock}
        _rts.inc("checkpoint_restores")
        return manifest

    def snapshot_info(self):
        """JSON-serializable view (never syncs)."""
        return {"enabled": _state["on"] and bool(_GLOBAL)
                and _GLOBAL[0] is self,
                "directory": self.directory, "keep": self.keep,
                "interval": self.interval,
                "async": self.async_write,
                "step_clock": self.step_clock,
                "last_good": dict(self.last_good)
                if self.last_good else None,
                "last_error": self.last_error,
                "totals": dict(self.totals)}


# ------------------------------------------------------ module surface


def enable(directory, interval=None, keep=None, async_write=None,
           prefix="ckpt"):
    """Create (or replace) the global :class:`CheckpointManager` and arm
    the guard-first ``Trainer.step`` hook (:func:`on_step`).  Returns
    the manager."""
    if interval is None:
        interval = int(os.environ.get("MXNET_TPU_CKPT_INTERVAL", "100"))
    if keep is None:
        keep = int(os.environ.get("MXNET_TPU_CKPT_KEEP", "5"))
    mgr = CheckpointManager(directory, keep=keep, interval=interval,
                            async_write=async_write, prefix=prefix)
    if _GLOBAL:
        _GLOBAL[0].close()
    _GLOBAL.clear()
    _GLOBAL.append(mgr)
    _state["on"] = True
    return mgr


def disable():
    """Disarm the hook; the manager flushes pending writes and stays
    readable."""
    _state["on"] = False
    if _GLOBAL:
        _GLOBAL[0].close()


def is_enabled():
    return _state["on"]


def manager():
    """The global manager while enabled, else None."""
    return _GLOBAL[0] if _state["on"] and _GLOBAL else None


def on_step(trainer, pin=False):
    """``Trainer.step`` hook: advance the global manager's step clock
    and auto-save at interval boundaries.  ONE dict read when disabled
    (the default) — safe on the hot path.

    ``pin=True`` (the compiled-step path) materializes each snapshot
    at capture: the whole-step program donates the param/optimizer
    buffers on the next call, so by-reference captures must be brought
    to host before then (``save_trainer``'s pin contract).

    The global clock assumes ONE Trainer drives the run (the reference
    training-loop shape).  Multi-trainer setups (e.g. GANs) should
    disable auto-checkpointing and call
    ``manager().save_trainer(trainer, step=...)`` per trainer with
    distinct prefixes — each manifest snapshots the params of the
    trainer it was captured from."""
    if not _state["on"]:
        return
    mgr = _GLOBAL[0]
    mgr.step_clock += 1
    if mgr.interval and mgr.step_clock % mgr.interval == 0:
        # step-anatomy checkpoint_write phase: the TRAINING-thread cost
        # only (async mode: the device-reference capture; sync mode:
        # the full write).  The background writer's commit time stays
        # in the checkpoint:write histogram, not in any step's window.
        ss_on = _stepstats._state["on"]
        if ss_on:
            ss_tok = _stepstats.begin()
        mgr.save_trainer(trainer, step=mgr.step_clock, pin=pin)
        if ss_on:
            _stepstats.end("checkpoint_write", ss_tok)


def load_aux(manifest):
    """Read a checkpoint's opaque ``aux.pkl`` sideband payload (see
    ``CheckpointManager.save``); None when the manifest carries none.
    The file's checksum was already verified by ``latest()``/``verify``
    before the manifest was handed out."""
    if not manifest or not manifest.get("has_aux"):
        return None
    with open(os.path.join(manifest["path"], "aux.pkl"), "rb") as f:
        return pickle.load(f)


def auto_resume(trainer=None, block=None, zero_step=None):
    """One call: restore the newest valid checkpoint from the global
    manager into ``trainer``/``block``.  Returns the resumed step (int)
    or None when checkpointing is off or nothing valid exists.

    ``zero_step`` (a ``GluonStep(..., zero=True)`` or
    ``ZeroCompiledStep``) selects the SHARDED resume path instead: the
    newest valid checkpoint's per-rank shard files are loaded and
    re-sharded onto the current mesh layout (``restore_zero`` — a run
    saved at one dp width resumes at another).  A newest checkpoint
    that is not sharded restores nothing (warned, returns None) rather
    than silently mixing the two formats."""
    mgr = manager()
    if mgr is None:
        return None
    if zero_step is not None:
        mgr.wait()
        manifest = mgr.latest()
        if manifest is None:
            return None
        if not manifest.get("shard_files"):
            warn_rate_limited(
                _logger(), "checkpoint:notsharded:%s" % manifest["path"],
                60, "auto_resume(zero_step=): newest checkpoint %s is "
                "not sharded — nothing restored (save with save_zero "
                "or pass trainer=/block= for the replicated format)",
                manifest["path"])
            return None
        step = zero_step.restore_zero(manifest, mgr=mgr)
        mgr.step_clock = step
        mgr.last_good = {"path": manifest["path"], "step": step}
        _rts.inc("checkpoint_restores")
        return step
    manifest = mgr.restore(trainer=trainer, block=block)
    return None if manifest is None else int(manifest.get("step", 0))


def lineage():
    """``{"last_good_path", "step"}`` of the newest committed (or
    restored) checkpoint — what the health layer's flight dump embeds so
    an operator knows exactly where to resume from.  None when off."""
    if not _state["on"] or not _GLOBAL:
        return None
    lg = _GLOBAL[0].last_good
    if lg is None:
        return {"last_good_path": None, "step": None,
                "directory": _GLOBAL[0].directory}
    return {"last_good_path": lg["path"], "step": lg["step"],
            "directory": _GLOBAL[0].directory}


def snapshot():
    """Global manager view, or a disabled stub."""
    if _GLOBAL:
        return _GLOBAL[0].snapshot_info()
    return {"enabled": False}


def reset():
    """Disable and drop the global manager (tests)."""
    _state["on"] = False
    if _GLOBAL:
        try:
            _GLOBAL[0].close()
        except Exception:
            pass
    _GLOBAL.clear()


# --------------------------------------------- legacy prefix/epoch API


def save_legacy(prefix, epoch, symbol, arg_params, aux_params):
    """The ``model.save_checkpoint`` file layout (``<prefix>-symbol.json``
    + ``<prefix>-<epoch:04d>.params``) written atomically, plus a
    sidecar ``<prefix>-<epoch:04d>.manifest.json`` carrying checksums so
    :func:`load_legacy` can detect torn/corrupt files."""
    from .ndarray import save as nd_save

    if symbol is not None:
        with atomic_write("%s-symbol.json" % prefix) as tmp:
            symbol.save(tmp)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    with atomic_write(param_name) as tmp:
        nd_save(tmp, save_dict)
    files = {os.path.basename(param_name):
             {"sha256": _sha256(param_name),
              "bytes": os.path.getsize(param_name)}}
    sym_name = "%s-symbol.json" % prefix
    if symbol is not None and os.path.exists(sym_name):
        files[os.path.basename(sym_name)] = {
            "sha256": _sha256(sym_name),
            "bytes": os.path.getsize(sym_name)}
    manifest = {"version": MANIFEST_VERSION, "epoch": int(epoch),
                "time": time.time(), "files": files}
    with atomic_write("%s-%04d.manifest.json" % (prefix, epoch)) as tmp:
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)


def load_legacy(prefix, epoch):
    """Verify (when the sidecar manifest exists) then load the legacy
    checkpoint files; a checksum mismatch raises a clear error instead
    of feeding half-written weights into a run."""
    from .base import MXNetError

    mpath = "%s-%04d.manifest.json" % (prefix, epoch)
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            manifest = None
        if manifest:
            d = os.path.dirname(os.path.abspath(mpath))
            for fname, meta in manifest.get("files", {}).items():
                fpath = os.path.join(d, fname)
                try:
                    ok = os.path.getsize(fpath) == meta["bytes"] and \
                        _sha256(fpath) == meta["sha256"]
                except OSError:
                    ok = False
                if not ok:
                    raise MXNetError(
                        "checkpoint file %s fails its manifest checksum "
                        "(%s) — the file is torn or corrupt; restore an "
                        "earlier epoch or a CheckpointManager checkpoint"
                        % (fpath, mpath))
    from .ndarray import load as nd_load
    from .symbol import load as sym_load

    symbol = sym_load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def _activate_from_env():
    directory = os.environ.get("MXNET_TPU_CKPT")
    if directory:
        enable(directory)
        return True
    return False


_activate_from_env()
