"""mx.contrib — experimental subsystems (reference: python/mxnet/contrib/).

Currently: quantization (INT8), onnx (import/export).
"""

from . import quantization  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import tensorboard  # noqa: F401
from . import text  # noqa: F401

try:  # onnx codec is self-contained but optional
    from . import onnx  # noqa: F401
except ImportError:
    pass
