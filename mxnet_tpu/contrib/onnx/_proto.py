"""Minimal protobuf wire-format codec for ONNX messages.

The ``onnx`` pip package (protobuf codegen) is not in this image, and ONNX
support shouldn't require it: the wire format is stable and small.  This
module implements the subset of protobuf (varint / 64-bit / length-
delimited / 32-bit wire types, packed repeated numerics) needed for the
ONNX ModelProto tree, driven by schema tables transcribed from the public
onnx.proto3 specification.

Messages are plain dicts; repeated fields are lists.  Unknown fields are
skipped on decode (forward-compatible) and never emitted on encode.

Reference parity: python/mxnet/contrib/onnx (mx2onnx/onnx2mx) uses the
onnx package for the same ModelProto surface.
"""

from __future__ import annotations

import struct

# ---------------------------------------------------------------- schemas
# field number -> (name, kind); kind: varint | sint (zigzag unused by onnx)
# | str | bytes | float | double | msg:<Name>; repeated fields end with '*'.
SCHEMAS = {
    "ModelProto": {
        1: ("ir_version", "varint"),
        2: ("producer_name", "str"),
        3: ("producer_version", "str"),
        4: ("domain", "str"),
        5: ("model_version", "varint"),
        6: ("doc_string", "str"),
        7: ("graph", "msg:GraphProto"),
        8: ("opset_import", "msg:OperatorSetIdProto*"),
    },
    "OperatorSetIdProto": {
        1: ("domain", "str"),
        2: ("version", "varint"),
    },
    "GraphProto": {
        1: ("node", "msg:NodeProto*"),
        2: ("name", "str"),
        5: ("initializer", "msg:TensorProto*"),
        10: ("doc_string", "str"),
        11: ("input", "msg:ValueInfoProto*"),
        12: ("output", "msg:ValueInfoProto*"),
        13: ("value_info", "msg:ValueInfoProto*"),
    },
    "NodeProto": {
        1: ("input", "str*"),
        2: ("output", "str*"),
        3: ("name", "str"),
        4: ("op_type", "str"),
        5: ("attribute", "msg:AttributeProto*"),
        6: ("doc_string", "str"),
        7: ("domain", "str"),
    },
    "AttributeProto": {
        1: ("name", "str"),
        2: ("f", "float"),
        3: ("i", "varint"),
        4: ("s", "bytes"),
        5: ("t", "msg:TensorProto"),
        6: ("g", "msg:GraphProto"),
        7: ("floats", "float*"),
        8: ("ints", "varint*"),
        9: ("strings", "bytes*"),
        10: ("tensors", "msg:TensorProto*"),
        11: ("graphs", "msg:GraphProto*"),
        20: ("type", "varint"),
    },
    "TensorProto": {
        1: ("dims", "varint*"),
        2: ("data_type", "varint"),
        4: ("float_data", "float*"),
        5: ("int32_data", "varint*"),
        6: ("string_data", "bytes*"),
        7: ("int64_data", "varint*"),
        8: ("name", "str"),
        9: ("raw_data", "bytes"),
        10: ("double_data", "double*"),
        11: ("uint64_data", "varint*"),
    },
    "ValueInfoProto": {
        1: ("name", "str"),
        2: ("type", "msg:TypeProto"),
        3: ("doc_string", "str"),
    },
    "TypeProto": {
        1: ("tensor_type", "msg:TypeProtoTensor"),
    },
    "TypeProtoTensor": {
        1: ("elem_type", "varint"),
        2: ("shape", "msg:TensorShapeProto"),
    },
    "TensorShapeProto": {
        1: ("dim", "msg:TensorShapeDim*"),
    },
    "TensorShapeDim": {
        1: ("dim_value", "varint"),
        2: ("dim_param", "str"),
    },
}

# ONNX TensorProto.DataType (public enum values)
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13

# ONNX AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# ---------------------------------------------------------------- encode
def _enc_varint(v):
    if v < 0:
        v += 1 << 64  # two's-complement 64-bit (proto int64 negatives)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field_num, wire):
    return _enc_varint((field_num << 3) | wire)


def _enc_scalar(num, kind, v):
    if kind == "varint":
        return _key(num, 0) + _enc_varint(int(v))
    if kind == "float":
        return _key(num, 5) + struct.pack("<f", float(v))
    if kind == "double":
        return _key(num, 1) + struct.pack("<d", float(v))
    if kind in ("str", "bytes"):
        b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        return _key(num, 2) + _enc_varint(len(b)) + b
    raise ValueError(kind)


def encode(msg, schema_name):
    """dict -> wire bytes following SCHEMAS[schema_name]."""
    schema = SCHEMAS[schema_name]
    by_name = {name: (num, kind) for num, (name, kind) in schema.items()}
    out = bytearray()
    for name, value in msg.items():
        if name not in by_name or value is None:
            continue
        num, kind = by_name[name]
        repeated = kind.endswith("*")
        base = kind[:-1] if repeated else kind
        if base.startswith("msg:"):
            sub = base[4:]
            items = value if repeated else [value]
            for item in items:
                b = encode(item, sub)
                out += _key(num, 2) + _enc_varint(len(b)) + b
        elif repeated:
            items = list(value)
            if not items:
                continue
            if base == "varint":  # packed (proto3 default)
                body = b"".join(_enc_varint(int(x)) for x in items)
                out += _key(num, 2) + _enc_varint(len(body)) + body
            elif base == "float":
                body = struct.pack("<%df" % len(items),
                                   *[float(x) for x in items])
                out += _key(num, 2) + _enc_varint(len(body)) + body
            elif base == "double":
                body = struct.pack("<%dd" % len(items),
                                   *[float(x) for x in items])
                out += _key(num, 2) + _enc_varint(len(body)) + body
            else:  # strings/bytes are never packed
                for item in items:
                    out += _enc_scalar(num, base, item)
        else:
            out += _enc_scalar(num, base, value)
    return bytes(out)


# ---------------------------------------------------------------- decode
def _dec_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    if result >= 1 << 63:  # negative int64
        result -= 1 << 64
    return result, pos


def decode(buf, schema_name):
    """wire bytes -> dict; unknown fields skipped."""
    schema = SCHEMAS[schema_name]
    msg = {}
    pos = 0
    end = len(buf)
    while pos < end:
        keyv, pos = _dec_varint(buf, pos)
        num, wire = keyv >> 3, keyv & 7
        entry = schema.get(num)
        if entry is None:  # skip unknown field
            if wire == 0:
                _, pos = _dec_varint(buf, pos)
            elif wire == 1:
                pos += 8
            elif wire == 2:
                ln, pos = _dec_varint(buf, pos)
                pos += ln
            elif wire == 5:
                pos += 4
            else:
                raise ValueError("unsupported wire type %d" % wire)
            continue
        name, kind = entry
        repeated = kind.endswith("*")
        base = kind[:-1] if repeated else kind
        if wire == 0:
            v, pos = _dec_varint(buf, pos)
        elif wire == 1:
            v = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wire == 5:
            v = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif wire == 2:
            ln, pos = _dec_varint(buf, pos)
            chunk = buf[pos:pos + ln]
            pos += ln
            if base.startswith("msg:"):
                v = decode(chunk, base[4:])
            elif base == "str":
                v = chunk.decode("utf-8", "replace")
            elif base == "bytes":
                v = bytes(chunk)
            elif base in ("varint", "float", "double") and repeated:
                # packed repeated numerics
                vals = []
                p = 0
                if base == "varint":
                    while p < len(chunk):
                        x, p = _dec_varint(chunk, p)
                        vals.append(x)
                elif base == "float":
                    vals = list(struct.unpack("<%df" % (len(chunk) // 4),
                                              chunk))
                else:
                    vals = list(struct.unpack("<%dd" % (len(chunk) // 8),
                                              chunk))
                msg.setdefault(name, []).extend(vals)
                continue
            else:
                raise ValueError("field %s: unexpected length-delimited "
                                 "payload" % name)
        else:
            raise ValueError("unsupported wire type %d" % wire)
        if repeated:
            msg.setdefault(name, []).append(v)
        else:
            msg[name] = v
    return msg
