"""Symbol graph -> ONNX ModelProto (reference:
python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

Each supported operator maps to standard ONNX ops (opset 12 semantics for
the subset used).  Parameters become graph initializers.
"""

from __future__ import annotations

import numpy as _np

from . import _proto as P

_DTYPE_TO_ONNX = {
    _np.dtype(_np.float32): P.FLOAT,
    _np.dtype(_np.float64): P.DOUBLE,
    _np.dtype(_np.float16): P.FLOAT16,
    _np.dtype(_np.int32): P.INT32,
    _np.dtype(_np.int64): P.INT64,
    _np.dtype(_np.int8): P.INT8,
    _np.dtype(_np.uint8): P.UINT8,
    _np.dtype(_np.bool_): P.BOOL,
}


def tensor_proto(name, arr):
    # ascontiguousarray promotes 0-d to (1,); keep the true shape so
    # scalar initializers (Clip bounds, Pad value) stay ONNX scalars
    shape = _np.shape(arr)
    arr = _np.ascontiguousarray(arr).reshape(shape)
    return {"name": name, "dims": list(shape),
            "data_type": _DTYPE_TO_ONNX[arr.dtype],
            "raw_data": arr.tobytes()}


def _attr(name, value):
    if isinstance(value, float):
        return {"name": name, "f": value, "type": P.A_FLOAT}
    if isinstance(value, (bool, int)):
        return {"name": name, "i": int(value), "type": P.A_INT}
    if isinstance(value, str):
        return {"name": name, "s": value.encode(), "type": P.A_STRING}
    if isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            return {"name": name, "floats": [float(v) for v in value],
                    "type": P.A_FLOATS}
        return {"name": name, "ints": [int(v) for v in value],
                "type": P.A_INTS}
    raise ValueError("unsupported attr %s=%r" % (name, value))


def _node(op_type, inputs, outputs, name, **attrs):
    return {"op_type": op_type, "input": list(inputs),
            "output": list(outputs), "name": name,
            "attribute": [_attr(k, v) for k, v in attrs.items()]}


class _Exporter:
    def __init__(self, params, dtype=_np.float32):
        self.params = dict(params or {})
        self.nodes = []
        self.initializers = []
        self.extra_inputs = []  # shape tensors etc.
        self.counter = 0
        self.dtype = _np.dtype(dtype)  # the graph's tensor type T

    def tmp(self, hint):
        self.counter += 1
        return "%s_tmp%d" % (hint, self.counter)

    def const_i64(self, name, values):
        self.initializers.append(tensor_proto(
            name, _np.asarray(values, dtype=_np.int64)))
        return name

    def const_t(self, name, values):
        """Constant initializer in the graph dtype T — ONNX binary/
        variadic ops (Mul/Add/Pow/Min/Max/Pad/Clip) require both inputs
        to share T, so scalar operands must follow the exported graph's
        dtype rather than a hardcoded float32."""
        self.initializers.append(tensor_proto(
            name, _np.asarray(values, dtype=self.dtype)))
        return name

    def emit(self, *args, **kwargs):
        self.nodes.append(_node(*args, **kwargs))


def _entry_name(entry):
    node, idx = entry
    if node.op is None:
        return node.name
    if node.num_outputs > 1:
        return "%s_output%d" % (node.name, idx)
    return node.name + "_output"


def _conv_attrs(a, ndim):
    k = tuple(int(x) for x in a.get("kernel", ()))
    s = tuple(int(x) for x in a.get("stride", ())) or (1,) * ndim
    p = tuple(int(x) for x in a.get("pad", ())) or (0,) * ndim
    d = tuple(int(x) for x in a.get("dilate", ())) or (1,) * ndim
    return k, s, p, d


def _export_node(ex, node, ins, out):
    """Translate one mxnet-style node; ins/out are ONNX tensor names."""
    op, a, name = node.op, node.attrs, node.name
    if op == "FullyConnected":
        data = ins[0]
        if a.get("flatten", True):
            flat = ex.tmp(name)
            ex.emit("Flatten", [data], [flat], name + "_flat", axis=1)
            data = flat
        if a.get("no_bias", False):
            # Gemm requires C; emit MatMul with transposed weight instead
            wt = ex.tmp(name)
            ex.emit("Transpose", [ins[1]], [wt], name + "_wT", perm=[1, 0])
            ex.emit("MatMul", [data, wt], [out], name)
        else:
            ex.emit("Gemm", [data, ins[1], ins[2]], [out], name,
                    alpha=1.0, beta=1.0, transA=0, transB=1)
    elif op == "Convolution":
        ndim = len(tuple(a.get("kernel", ()))) or 2
        k, s, p, d = _conv_attrs(a, ndim)
        ex.emit("Conv", ins, [out], name, kernel_shape=list(k),
                strides=list(s), pads=list(p) * 2, dilations=list(d),
                group=int(a.get("num_group", 1)))
    elif op == "Deconvolution":
        ndim = len(tuple(a.get("kernel", ()))) or 2
        k, s, p, d = _conv_attrs(a, ndim)
        ex.emit("ConvTranspose", ins, [out], name, kernel_shape=list(k),
                strides=list(s), pads=list(p) * 2, dilations=list(d),
                group=int(a.get("num_group", 1)))
    elif op == "Activation":
        act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus", "softsign": "Softsign"}[
            a.get("act_type", "relu")]
        ex.emit(act, ins, [out], name)
    elif op == "LeakyReLU":
        act_type = a.get("act_type", "leaky")
        if act_type == "leaky":
            ex.emit("LeakyRelu", ins[:1], [out], name,
                    alpha=float(a.get("slope", 0.25)))
        elif act_type == "elu":
            ex.emit("Elu", ins[:1], [out], name,
                    alpha=float(a.get("slope", 0.25)))
        elif act_type == "prelu":
            ex.emit("PRelu", ins, [out], name)
        else:
            raise NotImplementedError("LeakyReLU %s" % act_type)
    elif op == "BatchNorm":
        # ins: data gamma beta moving_mean moving_var.  fix_gamma=True
        # (the mxnet default) means gamma is pinned to 1 — ONNX has no such
        # flag, so export a ones initializer in gamma's place.
        if a.get("fix_gamma", True):
            gname = ins[1]
            shape = _np.shape(ex.params.get(gname, ()))
            if not shape:
                shape = _np.shape(ex.params.get(ins[2], (1,)))
            fixed = name + "_gamma_fixed"
            ex.initializers.append(tensor_proto(
                fixed, _np.ones(shape, dtype=ex.dtype)))
            ins = [ins[0], fixed] + list(ins[2:])
        ex.emit("BatchNormalization", ins, [out], name,
                epsilon=float(a.get("eps", 1e-3)),
                momentum=float(a.get("momentum", 0.9)))
    elif op == "Pooling":
        k = tuple(int(x) for x in a.get("kernel", ()))
        ndim = len(k) or 2
        s = tuple(int(x) for x in a.get("stride", ())) or (1,) * ndim
        p = tuple(int(x) for x in a.get("pad", ())) or (0,) * ndim
        ptype = a.get("pool_type", "max")
        if a.get("global_pool", False):
            ex.emit({"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[
                ptype], ins, [out], name)
        else:
            onnx_op = {"max": "MaxPool", "avg": "AveragePool"}[ptype]
            kw = dict(kernel_shape=list(k), strides=list(s),
                      pads=list(p) * 2)
            if ptype == "avg":
                kw["count_include_pad"] = int(a.get("count_include_pad",
                                                    True))
            ex.emit(onnx_op, ins, [out], name, **kw)
    elif op in ("softmax", "SoftmaxOutput", "SoftmaxActivation"):
        axis = int(a.get("axis", -1)) if op == "softmax" else 1
        ex.emit("Softmax", ins[:1], [out], name, axis=axis)
    elif op == "LayerNorm":
        ex.emit("LayerNormalization", ins, [out], name,
                axis=int(a.get("axis", -1)),
                epsilon=float(a.get("eps", 1e-5)))
    elif op == "Concat":
        ex.emit("Concat", ins, [out], name, axis=int(a.get("dim", 1)))
    elif op == "Flatten":
        ex.emit("Flatten", ins, [out], name, axis=1)
    elif op in ("Reshape", "reshape"):
        shape = [int(x) for x in a.get("shape", ())]
        sname = ex.const_i64(ex.tmp(name + "_shape"), shape)
        ex.emit("Reshape", [ins[0], sname], [out], name)
    elif op == "transpose":
        axes = [int(x) for x in a.get("axes", ())]
        kw = {"perm": axes} if axes else {}
        ex.emit("Transpose", ins, [out], name, **kw)
    elif op == "Dropout":
        ex.emit("Dropout", ins, [out], name)
    elif op == "Embedding":
        # onnx Gather(weight, indices); mxnet Embedding(data, weight)
        idx = ex.tmp(name + "_idx")
        ex.emit("Cast", [ins[0]], [idx], name + "_cast", to=P.INT64)
        ex.emit("Gather", [ins[1], idx], [out], name, axis=0)
    elif op in ("elemwise_add", "_plus", "broadcast_add", "_add"):
        ex.emit("Add", ins, [out], name)
    elif op in ("elemwise_sub", "broadcast_sub", "_sub"):
        ex.emit("Sub", ins, [out], name)
    elif op in ("elemwise_mul", "broadcast_mul", "_mul"):
        ex.emit("Mul", ins, [out], name)
    elif op in ("elemwise_div", "broadcast_div", "_div"):
        ex.emit("Div", ins, [out], name)
    elif op == "dot":
        ex.emit("MatMul", ins, [out], name)
    elif op == "relu":
        ex.emit("Relu", ins, [out], name)
    elif op == "sigmoid":
        ex.emit("Sigmoid", ins, [out], name)
    elif op == "tanh":
        ex.emit("Tanh", ins, [out], name)
    elif op == "exp":
        ex.emit("Exp", ins, [out], name)
    elif op == "log":
        ex.emit("Log", ins, [out], name)
    elif op == "sqrt":
        ex.emit("Sqrt", ins, [out], name)
    elif op == "negative":
        ex.emit("Neg", ins, [out], name)
    elif op in ("abs",):
        ex.emit("Abs", ins, [out], name)
    elif op in ("floor",):
        ex.emit("Floor", ins, [out], name)
    elif op in ("ceil",):
        ex.emit("Ceil", ins, [out], name)
    elif op in ("reciprocal",):
        ex.emit("Reciprocal", ins, [out], name)
    elif op in ("broadcast_power", "_power", "elemwise_power", "_Power"):
        ex.emit("Pow", ins, [out], name)
    elif op in ("broadcast_maximum", "_maximum", "maximum"):
        ex.emit("Max", ins, [out], name)
    elif op in ("broadcast_minimum", "_minimum", "minimum"):
        ex.emit("Min", ins, [out], name)
    elif op == "hard_sigmoid":
        ex.emit("HardSigmoid", ins, [out], name,
                alpha=float(a.get("alpha", 0.2)),
                beta=float(a.get("beta", 0.5)))
    elif op == "LRN":
        ex.emit("LRN", ins, [out], name,
                alpha=float(a.get("alpha", 1e-4)),
                beta=float(a.get("beta", 0.75)),
                bias=float(a.get("knorm", 2.0)),
                size=int(a.get("nsize", 5)))
    elif op == "InstanceNorm":
        ex.emit("InstanceNormalization", ins, [out], name,
                epsilon=float(a.get("eps", 1e-3)))
    elif op == "argmax":
        if a.get("axis") is None:
            # axis=None means argmax over the FLATTENED array; ONNX
            # ArgMax has no such mode
            raise NotImplementedError(
                "ONNX export: argmax without axis (flatten semantics)")
        # mxnet argmax returns float32; ONNX ArgMax emits int64 — cast
        # back so typed consumers line up
        raw = ex.tmp(name + "_i64")
        ex.emit("ArgMax", ins, [raw], name,
                axis=int(a["axis"]),
                keepdims=int(a.get("keepdims", False)))
        ex.emit("Cast", [raw], [out], name + "_cast",
                to=_DTYPE_TO_ONNX[ex.dtype])
    elif op in ("sum", "sum_axis", "mean", "max", "min", "prod"):
        onnx_op = {"sum": "ReduceSum", "sum_axis": "ReduceSum",
                   "mean": "ReduceMean", "max": "ReduceMax",
                   "min": "ReduceMin", "prod": "ReduceProd"}[op]
        if a.get("exclude"):
            raise NotImplementedError(
                "ONNX export: reduce with exclude=True")
        axes = a.get("axis", None)
        kw = {}
        if axes is not None and axes != ():
            kw["axes"] = [int(x) for x in (axes if isinstance(
                axes, (tuple, list)) else (axes,))]
        ex.emit(onnx_op, ins, [out], name,
                keepdims=int(a.get("keepdims", False)), **kw)
    elif op == "squeeze":
        axes = a.get("axis", None)
        kw = {}
        if axes is not None and axes != ():
            kw["axes"] = [int(x) for x in (axes if isinstance(
                axes, (tuple, list)) else (axes,))]
        ex.emit("Squeeze", ins, [out], name, **kw)
    elif op == "expand_dims":
        ex.emit("Unsqueeze", ins, [out], name,
                axes=[int(a.get("axis", 0))])
    elif op == "slice_axis":
        ax = int(a.get("axis", 0))
        begin = int(a.get("begin", 0))
        end = a.get("end", None)
        end = int(end) if end is not None else _np.iinfo(_np.int64).max
        starts = ex.const_i64(ex.tmp(name + "_starts"), [begin])
        ends = ex.const_i64(ex.tmp(name + "_ends"), [end])
        axes_t = ex.const_i64(ex.tmp(name + "_axes"), [ax])
        ex.emit("Slice", [ins[0], starts, ends, axes_t], [out], name)
    elif op in ("pad", "Pad"):
        pw = [int(x) for x in a.get("pad_width", ())]
        ndim = len(pw) // 2
        pads = [pw[2 * i] for i in range(ndim)] + \
               [pw[2 * i + 1] for i in range(ndim)]
        pname = ex.const_i64(ex.tmp(name + "_pads"), pads)
        vname = ex.const_t(ex.tmp(name + "_value"),
                           float(a.get("constant_value", 0.0)))
        if a.get("mode", "constant") != "constant":
            raise NotImplementedError("ONNX export: pad mode %r"
                                      % a.get("mode"))
        ex.emit("Pad", [ins[0], pname, vname], [out], name,
                mode="constant")
    elif op == "SliceChannel":
        outs = out if isinstance(out, list) else [out]
        axis = int(a.get("axis", 1))
        if a.get("squeeze_axis"):
            raws = [ex.tmp(o) for o in outs]
            ex.emit("Split", ins, raws, name, axis=axis)
            for raw, o in zip(raws, outs):
                ex.emit("Squeeze", [raw], [o], o + "_sq", axes=[axis])
        else:
            ex.emit("Split", ins, outs, name, axis=axis)
    elif op in ("_mul_scalar", "_plus_scalar", "_minus_scalar",
                "_rminus_scalar", "_div_scalar", "_rdiv_scalar",
                "_power_scalar", "_rpower_scalar", "_maximum_scalar",
                "_minimum_scalar"):
        onnx_op, reversed_ = {
            "_mul_scalar": ("Mul", False), "_plus_scalar": ("Add", False),
            "_minus_scalar": ("Sub", False), "_rminus_scalar": ("Sub", True),
            "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
            "_power_scalar": ("Pow", False), "_rpower_scalar": ("Pow", True),
            "_maximum_scalar": ("Max", False),
            "_minimum_scalar": ("Min", False)}[op]
        sname = ex.const_t(ex.tmp(name + "_scalar"),
                           float(a.get("scalar", 0.0)))
        pair = [sname, ins[0]] if reversed_ else [ins[0], sname]
        ex.emit(onnx_op, pair, [out], name)
    elif op == "UpSampling":
        if a.get("sample_type", "nearest") != "nearest":
            raise NotImplementedError("ONNX export: UpSampling %r"
                                      % a.get("sample_type"))
        scale = float(a.get("scale", 2))
        roi = ex.tmp(name + "_roi")
        ex.initializers.append(tensor_proto(
            roi, _np.zeros((0,), _np.float32)))
        scales = ex.tmp(name + "_scales")
        ex.initializers.append(tensor_proto(
            scales, _np.asarray([1.0, 1.0, scale, scale], _np.float32)))
        ex.emit("Resize", [ins[0], roi, scales], [out], name,
                mode="nearest")
    elif op == "clip":
        mn = ex.const_t(ex.tmp(name + "_min"), float(a.get("a_min", 0.0)))
        mx = ex.const_t(ex.tmp(name + "_max"), float(a.get("a_max", 1.0)))
        ex.emit("Clip", [ins[0], mn, mx], [out], name)
    else:
        raise NotImplementedError(
            "ONNX export: operator %r not supported" % op)


def export_symbol(sym, params, input_shapes, input_dtype=_np.float32,
                  opset=12):
    """-> ModelProto dict.  `params` maps arg/aux name -> numpy array."""
    ex = _Exporter(params, dtype=input_dtype)
    params = ex.params
    topo = sym._topo_nodes()
    out_names = []
    for node in topo:
        if node.op is None:
            continue
        ins = [_entry_name(e) for e in node.inputs]
        outs = ["%s_output%d" % (node.name, i) for i in
                range(node.num_outputs)] if node.num_outputs > 1 else \
            [node.name + "_output"]
        _export_node(ex, node, ins, outs[0] if len(outs) == 1 else outs)

    graph_inputs = []
    initializers = ex.initializers
    shape_map = dict(input_shapes)
    for node in topo:
        if node.op is not None:
            continue
        if node.name in params:
            initializers.append(tensor_proto(node.name,
                                             _np.asarray(params[node.name])))
        else:
            shape = shape_map.get(node.name)
            if shape is None:
                raise ValueError("need input shape for %r" % node.name)
            graph_inputs.append(_value_info(node.name, shape, input_dtype))

    outputs = []
    for e in sym._outputs:
        out_names.append(_entry_name(e))
        outputs.append({"name": out_names[-1]})
    graph = {"node": ex.nodes, "name": "mxnet_tpu_graph",
             "initializer": initializers, "input": graph_inputs,
             "output": outputs}
    return {"ir_version": 7, "producer_name": "mxnet_tpu",
            "producer_version": "0.1", "graph": graph,
            "opset_import": [{"domain": "", "version": opset}]}


def _value_info(name, shape, dtype):
    return {"name": name, "type": {"tensor_type": {
        "elem_type": _DTYPE_TO_ONNX[_np.dtype(dtype)],
        "shape": {"dim": [{"dim_value": int(d)} for d in shape]}}}}
