"""ONNX ModelProto -> Symbol + params (reference:
python/mxnet/contrib/onnx/onnx2mx/import_model.py + _op_translations.py).
"""

from __future__ import annotations

import numpy as _np

from . import _proto as P

_ONNX_TO_DTYPE = {
    P.FLOAT: _np.float32, P.DOUBLE: _np.float64, P.FLOAT16: _np.float16,
    P.INT32: _np.int32, P.INT64: _np.int64, P.INT8: _np.int8,
    P.UINT8: _np.uint8, P.BOOL: _np.bool_,
}


def tensor_to_numpy(t):
    dtype = _np.dtype(_ONNX_TO_DTYPE[t["data_type"]])
    dims = tuple(t.get("dims", ()))
    if "raw_data" in t and t["raw_data"]:
        arr = _np.frombuffer(t["raw_data"], dtype=dtype)
    elif t.get("float_data"):
        arr = _np.asarray(t["float_data"], dtype=dtype)
    elif t.get("int64_data"):
        arr = _np.asarray(t["int64_data"], dtype=dtype)
    elif t.get("int32_data"):
        arr = _np.asarray(t["int32_data"], dtype=dtype)
    elif t.get("double_data"):
        arr = _np.asarray(t["double_data"], dtype=dtype)
    else:
        arr = _np.zeros(dims, dtype=dtype)
    return arr.reshape(dims).copy()


def _attrs(node):
    out = {}
    for a in node.get("attribute", []):
        t = a.get("type")
        if t == P.A_FLOAT:
            out[a["name"]] = a.get("f", 0.0)
        elif t == P.A_INT:
            out[a["name"]] = a.get("i", 0)
        elif t == P.A_STRING:
            out[a["name"]] = a.get("s", b"").decode("utf-8", "replace")
        elif t == P.A_FLOATS:
            out[a["name"]] = list(a.get("floats", []))
        elif t == P.A_INTS:
            out[a["name"]] = list(a.get("ints", []))
        elif t == P.A_TENSOR:
            out[a["name"]] = tensor_to_numpy(a["t"])
        else:
            out[a["name"]] = a
    return out


def _pads(attrs, ndim):
    p = attrs.get("pads", [0] * (2 * ndim))
    begin, end = p[:ndim], p[ndim:]
    if list(begin) != list(end):
        raise NotImplementedError("asymmetric ONNX pads %s" % (p,))
    return tuple(int(x) for x in begin)


def _import_node(sym_mod, node, env, consts):
    """env: tensor name -> Symbol; consts: name -> numpy (initializers)."""
    op = node["op_type"]
    a = _attrs(node)
    ins = [env[i] for i in node["input"] if i]
    name = node.get("name") or node["output"][0]
    S = sym_mod

    def const_of(i):
        return consts.get(node["input"][i])

    if op == "Gemm":
        assert a.get("transB", 0) == 1 and a.get("transA", 0) == 0, \
            "only Gemm(transB=1) imported"
        num_hidden = const_of(1).shape[0] if const_of(1) is not None else None
        out = S.FullyConnected(ins[0], ins[1], ins[2],
                               num_hidden=num_hidden, flatten=False,
                               name=name)
    elif op == "MatMul":
        out = S.dot(ins[0], ins[1], name=name)
    elif op == "Conv":
        k = tuple(a.get("kernel_shape", ()))
        out = S.Convolution(
            *ins, kernel=k, num_filter=(const_of(1).shape[0]
                                        if const_of(1) is not None else 1),
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            dilate=tuple(a.get("dilations", (1,) * len(k))),
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) < 3, name=name)
    elif op == "ConvTranspose":
        k = tuple(a.get("kernel_shape", ()))
        w = const_of(1)
        out = S.Deconvolution(
            *ins, kernel=k,
            num_filter=(w.shape[1] * int(a.get("group", 1))
                        if w is not None else 1),
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) < 3, name=name)
    elif op == "BatchNormalization":
        out = S.BatchNorm(*ins, eps=float(a.get("epsilon", 1e-5)),
                          momentum=float(a.get("momentum", 0.9)),
                          fix_gamma=False, name=name)
    elif op in ("MaxPool", "AveragePool"):
        k = tuple(a.get("kernel_shape", ()))
        out = S.Pooling(
            ins[0], kernel=k,
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            pool_type="max" if op == "MaxPool" else "avg",
            count_include_pad=bool(a.get("count_include_pad", 1)),
            name=name)
    elif op in ("GlobalMaxPool", "GlobalAveragePool"):
        out = S.Pooling(ins[0], global_pool=True, kernel=(1, 1),
                        pool_type="max" if op == "GlobalMaxPool" else "avg",
                        name=name)
    elif op == "Relu":
        out = S.Activation(ins[0], act_type="relu", name=name)
    elif op == "Sigmoid":
        out = S.Activation(ins[0], act_type="sigmoid", name=name)
    elif op == "Tanh":
        out = S.Activation(ins[0], act_type="tanh", name=name)
    elif op == "Softplus":
        out = S.Activation(ins[0], act_type="softrelu", name=name)
    elif op == "LeakyRelu":
        out = S.LeakyReLU(ins[0], act_type="leaky",
                          slope=float(a.get("alpha", 0.01)), name=name)
    elif op == "Elu":
        out = S.LeakyReLU(ins[0], act_type="elu",
                          slope=float(a.get("alpha", 1.0)), name=name)
    elif op == "PRelu":
        out = S.LeakyReLU(ins[0], ins[1], act_type="prelu", name=name)
    elif op == "Softmax":
        out = S.softmax(ins[0], axis=int(a.get("axis", -1)), name=name)
    elif op == "LayerNormalization":
        out = S.LayerNorm(*ins, axis=int(a.get("axis", -1)),
                          eps=float(a.get("epsilon", 1e-5)), name=name)
    elif op == "Concat":
        out = S.Concat(*ins, dim=int(a.get("axis", 1)), name=name)
    elif op == "Flatten":
        out = S.Flatten(ins[0], name=name)
    elif op == "Reshape":
        shape = const_of(1)
        if shape is None:
            raise NotImplementedError("dynamic Reshape shape")
        out = S.reshape(ins[0], shape=tuple(int(x) for x in shape),
                        name=name)
    elif op == "Transpose":
        out = S.transpose(ins[0], axes=tuple(a.get("perm", ())), name=name)
    elif op == "Dropout":
        out = S.Dropout(ins[0], name=name)
    elif op == "Cast":
        out = S.cast(ins[0],
                     dtype=_np.dtype(_ONNX_TO_DTYPE[a["to"]]).name,
                     name=name)
    elif op == "Gather":
        # Gather(weight, indices, axis=0) == Embedding(indices, weight)
        w = const_of(0)
        if int(a.get("axis", 0)) == 0 and w is not None:
            out = S.Embedding(ins[1], ins[0], input_dim=w.shape[0],
                              output_dim=w.shape[1], name=name)
        else:
            out = S.take(ins[0], ins[1], axis=int(a.get("axis", 0)),
                         name=name)
    elif op == "Add":
        out = S.broadcast_add(ins[0], ins[1], name=name)
    elif op == "Sub":
        out = S.broadcast_sub(ins[0], ins[1], name=name)
    elif op == "Mul":
        out = S.broadcast_mul(ins[0], ins[1], name=name)
    elif op == "Div":
        out = S.broadcast_div(ins[0], ins[1], name=name)
    elif op == "Exp":
        out = S.exp(ins[0], name=name)
    elif op == "Log":
        out = S.log(ins[0], name=name)
    elif op == "Sqrt":
        out = S.sqrt(ins[0], name=name)
    elif op == "Neg":
        out = S.negative(ins[0], name=name)
    elif op == "Clip":
        a_min = const_of(1)
        a_max = const_of(2)
        out = S.clip(ins[0],
                     a_min=float(a_min) if a_min is not None else -3.4e38,
                     a_max=float(a_max) if a_max is not None else 3.4e38,
                     name=name)
    elif op == "ReduceSum":
        out = S.sum(ins[0], axis=tuple(a.get("axes", ())) or None,
                    keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op == "ReduceMean":
        out = S.mean(ins[0], axis=tuple(a.get("axes", ())) or None,
                     keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op == "Identity":
        out = ins[0]
    else:
        raise NotImplementedError("ONNX import: op %r not supported" % op)

    outputs = node["output"]
    if len(outputs) == 1:
        env[outputs[0]] = out
    else:
        for i, oname in enumerate(outputs):
            if oname:
                env[oname] = out[i]


def import_graph(graph):
    """GraphProto dict -> (Symbol, arg_params, aux_params)."""
    from ... import symbol as S
    from ...ndarray import array

    consts = {t["name"]: tensor_to_numpy(t)
              for t in graph.get("initializer", [])}
    env = {}
    for vi in graph.get("input", []):
        name = vi["name"]
        env[name] = S.Variable(name)
    for cname in consts:
        if cname not in env:
            env[cname] = S.Variable(cname)
    for node in graph.get("node", []):
        _import_node(S, node, env, consts)
    outs = [env[o["name"]] for o in graph.get("output", [])]
    sym = outs[0] if len(outs) == 1 else S.Group(outs)

    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {}
    aux_params = {}
    for name, arr in consts.items():
        if name in aux_names:
            aux_params[name] = array(arr)
        elif name in arg_names:
            arg_params[name] = array(arr)
    return sym, arg_params, aux_params
