"""ONNX ModelProto -> Symbol + params (reference:
python/mxnet/contrib/onnx/onnx2mx/import_model.py + _op_translations.py).
"""

from __future__ import annotations

import numpy as _np

from . import _proto as P

_ONNX_TO_DTYPE = {
    P.FLOAT: _np.float32, P.DOUBLE: _np.float64, P.FLOAT16: _np.float16,
    P.INT32: _np.int32, P.INT64: _np.int64, P.INT8: _np.int8,
    P.UINT8: _np.uint8, P.BOOL: _np.bool_,
}


def tensor_to_numpy(t):
    dtype = _np.dtype(_ONNX_TO_DTYPE[t["data_type"]])
    dims = tuple(t.get("dims", ()))
    if "raw_data" in t and t["raw_data"]:
        arr = _np.frombuffer(t["raw_data"], dtype=dtype)
    elif t.get("float_data"):
        arr = _np.asarray(t["float_data"], dtype=dtype)
    elif t.get("int64_data"):
        arr = _np.asarray(t["int64_data"], dtype=dtype)
    elif t.get("int32_data"):
        arr = _np.asarray(t["int32_data"], dtype=dtype)
    elif t.get("double_data"):
        arr = _np.asarray(t["double_data"], dtype=dtype)
    else:
        arr = _np.zeros(dims, dtype=dtype)
    return arr.reshape(dims).copy()


def _attrs(node):
    out = {}
    for a in node.get("attribute", []):
        t = a.get("type")
        if t == P.A_FLOAT:
            out[a["name"]] = a.get("f", 0.0)
        elif t == P.A_INT:
            out[a["name"]] = a.get("i", 0)
        elif t == P.A_STRING:
            out[a["name"]] = a.get("s", b"").decode("utf-8", "replace")
        elif t == P.A_FLOATS:
            out[a["name"]] = list(a.get("floats", []))
        elif t == P.A_INTS:
            out[a["name"]] = list(a.get("ints", []))
        elif t == P.A_TENSOR:
            out[a["name"]] = tensor_to_numpy(a["t"])
        else:
            out[a["name"]] = a
    return out


def _pads(attrs, ndim):
    p = attrs.get("pads", [0] * (2 * ndim))
    begin, end = p[:ndim], p[ndim:]
    if list(begin) != list(end):
        raise NotImplementedError("asymmetric ONNX pads %s" % (p,))
    return tuple(int(x) for x in begin)


def _import_node(sym_mod, node, env, consts):
    """env: tensor name -> Symbol; consts: name -> numpy (initializers)."""
    op = node["op_type"]
    a = _attrs(node)
    ins = [env[i] for i in node["input"] if i]
    name = node.get("name") or node["output"][0]
    S = sym_mod

    def const_of(i):
        return consts.get(node["input"][i])

    if op == "Gemm":
        assert a.get("transB", 0) == 1 and a.get("transA", 0) == 0, \
            "only Gemm(transB=1) imported"
        num_hidden = const_of(1).shape[0] if const_of(1) is not None else None
        out = S.FullyConnected(ins[0], ins[1], ins[2],
                               num_hidden=num_hidden, flatten=False,
                               name=name)
    elif op == "MatMul":
        out = S.dot(ins[0], ins[1], name=name)
    elif op == "Conv":
        k = tuple(a.get("kernel_shape", ()))
        out = S.Convolution(
            *ins, kernel=k, num_filter=(const_of(1).shape[0]
                                        if const_of(1) is not None else 1),
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            dilate=tuple(a.get("dilations", (1,) * len(k))),
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) < 3, name=name)
    elif op == "ConvTranspose":
        k = tuple(a.get("kernel_shape", ()))
        w = const_of(1)
        out = S.Deconvolution(
            *ins, kernel=k,
            num_filter=(w.shape[1] * int(a.get("group", 1))
                        if w is not None else 1),
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) < 3, name=name)
    elif op == "BatchNormalization":
        out = S.BatchNorm(*ins, eps=float(a.get("epsilon", 1e-5)),
                          momentum=float(a.get("momentum", 0.9)),
                          fix_gamma=False, name=name)
    elif op in ("MaxPool", "AveragePool"):
        k = tuple(a.get("kernel_shape", ()))
        out = S.Pooling(
            ins[0], kernel=k,
            stride=tuple(a.get("strides", (1,) * len(k))),
            pad=_pads(a, len(k)),
            pool_type="max" if op == "MaxPool" else "avg",
            count_include_pad=bool(a.get("count_include_pad", 1)),
            name=name)
    elif op in ("GlobalMaxPool", "GlobalAveragePool"):
        out = S.Pooling(ins[0], global_pool=True, kernel=(1, 1),
                        pool_type="max" if op == "GlobalMaxPool" else "avg",
                        name=name)
    elif op == "Relu":
        out = S.Activation(ins[0], act_type="relu", name=name)
    elif op == "Sigmoid":
        out = S.Activation(ins[0], act_type="sigmoid", name=name)
    elif op == "Tanh":
        out = S.Activation(ins[0], act_type="tanh", name=name)
    elif op == "Softplus":
        out = S.Activation(ins[0], act_type="softrelu", name=name)
    elif op == "LeakyRelu":
        out = S.LeakyReLU(ins[0], act_type="leaky",
                          slope=float(a.get("alpha", 0.01)), name=name)
    elif op == "Elu":
        out = S.LeakyReLU(ins[0], act_type="elu",
                          slope=float(a.get("alpha", 1.0)), name=name)
    elif op == "PRelu":
        out = S.LeakyReLU(ins[0], ins[1], act_type="prelu", name=name)
    elif op == "Softmax":
        out = S.softmax(ins[0], axis=int(a.get("axis", -1)), name=name)
    elif op == "LayerNormalization":
        out = S.LayerNorm(*ins, axis=int(a.get("axis", -1)),
                          eps=float(a.get("epsilon", 1e-5)), name=name)
    elif op == "Concat":
        out = S.Concat(*ins, dim=int(a.get("axis", 1)), name=name)
    elif op == "Flatten":
        out = S.Flatten(ins[0], name=name)
    elif op == "Reshape":
        shape = const_of(1)
        if shape is None:
            raise NotImplementedError("dynamic Reshape shape")
        out = S.reshape(ins[0], shape=tuple(int(x) for x in shape),
                        name=name)
    elif op == "Transpose":
        out = S.transpose(ins[0], axes=tuple(a.get("perm", ())), name=name)
    elif op == "Dropout":
        out = S.Dropout(ins[0], name=name)
    elif op == "Cast":
        out = S.cast(ins[0],
                     dtype=_np.dtype(_ONNX_TO_DTYPE[a["to"]]).name,
                     name=name)
    elif op == "Gather":
        # Gather(weight, indices, axis=0) == Embedding(indices, weight)
        w = const_of(0)
        if int(a.get("axis", 0)) == 0 and w is not None:
            out = S.Embedding(ins[1], ins[0], input_dim=w.shape[0],
                              output_dim=w.shape[1], name=name)
        else:
            out = S.take(ins[0], ins[1], axis=int(a.get("axis", 0)),
                         name=name)
    elif op == "Add":
        out = S.broadcast_add(ins[0], ins[1], name=name)
    elif op == "Sub":
        out = S.broadcast_sub(ins[0], ins[1], name=name)
    elif op == "Mul":
        out = S.broadcast_mul(ins[0], ins[1], name=name)
    elif op == "Div":
        out = S.broadcast_div(ins[0], ins[1], name=name)
    elif op == "Exp":
        out = S.exp(ins[0], name=name)
    elif op == "Log":
        out = S.log(ins[0], name=name)
    elif op == "Sqrt":
        out = S.sqrt(ins[0], name=name)
    elif op == "Neg":
        out = S.negative(ins[0], name=name)
    elif op == "Clip":
        a_min = const_of(1)
        a_max = const_of(2)
        out = S.clip(
            ins[0],
            a_min=(float(_np.asarray(a_min).ravel()[0])
                   if a_min is not None else -3.4e38),
            a_max=(float(_np.asarray(a_max).ravel()[0])
                   if a_max is not None else 3.4e38),
            name=name)
    elif op == "ReduceSum":
        out = S.sum(ins[0], axis=tuple(a.get("axes", ())) or None,
                    keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op == "ReduceMean":
        out = S.mean(ins[0], axis=tuple(a.get("axes", ())) or None,
                     keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op == "Identity":
        out = ins[0]
    elif op == "Pow":
        out = S.broadcast_power(ins[0], ins[1], name=name)
    elif op in ("Max", "Min"):
        fn = S.broadcast_maximum if op == "Max" else S.broadcast_minimum
        out = ins[0]
        for other in ins[1:]:
            out = fn(out, other)
    elif op == "Abs":
        out = S.abs(ins[0], name=name)
    elif op == "Floor":
        out = S.floor(ins[0], name=name)
    elif op == "Ceil":
        out = S.ceil(ins[0], name=name)
    elif op == "Reciprocal":
        out = S.reciprocal(ins[0], name=name)
    elif op == "HardSigmoid":
        out = S.hard_sigmoid(ins[0], alpha=float(a.get("alpha", 0.2)),
                             beta=float(a.get("beta", 0.5)), name=name)
    elif op == "LRN":
        out = S.LRN(ins[0], alpha=float(a.get("alpha", 1e-4)),
                    beta=float(a.get("beta", 0.75)),
                    knorm=float(a.get("bias", 1.0)),
                    nsize=int(a["size"]), name=name)
    elif op == "InstanceNormalization":
        out = S.InstanceNorm(*ins, eps=float(a.get("epsilon", 1e-5)),
                             name=name)
    elif op == "ArgMax":
        out = S.argmax(ins[0], axis=int(a.get("axis", 0)),
                       keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
        fn = {"ReduceMax": S.max, "ReduceMin": S.min,
              "ReduceProd": S.prod}[op]
        out = fn(ins[0], axis=tuple(a.get("axes", ())) or None,
                 keepdims=bool(a.get("keepdims", 1)), name=name)
    elif op == "Squeeze":
        axes = a.get("axes")
        if axes is None and len(node["input"]) > 1:
            axes = [int(x) for x in const_of(1)]
        out = S.squeeze(ins[0], axis=tuple(axes) if axes else None,
                        name=name)
    elif op == "Unsqueeze":
        axes = a.get("axes")
        if axes is None and len(node["input"]) > 1:
            axes = [int(x) for x in const_of(1)]
        if any(int(ax) < 0 for ax in axes):
            # negative axes index the OUTPUT rank, which we cannot know
            # without shape inference here
            raise NotImplementedError(
                "ONNX Unsqueeze with negative axes %s" % (axes,))
        out = ins[0]
        for ax in sorted(int(x) for x in axes):
            out = S.expand_dims(out, axis=ax)
    elif op == "Slice":
        if "starts" in a:                      # opset < 10: attributes
            starts, ends = a["starts"], a["ends"]
            axes = a.get("axes", list(range(len(starts))))
        else:                                  # opset >= 10: const inputs
            starts = [int(x) for x in const_of(1)]
            ends = [int(x) for x in const_of(2)]
            axes = ([int(x) for x in const_of(3)]
                    if len(node["input"]) > 3 and const_of(3) is not None
                    else list(range(len(starts))))
            if len(node["input"]) > 4 and const_of(4) is not None and \
                    any(int(x) != 1 for x in const_of(4)):
                raise NotImplementedError("ONNX Slice with steps != 1")
        out = ins[0]
        big = int(_np.iinfo(_np.int64).max)
        for ax, b, e in zip(axes, starts, ends):
            out = S.slice_axis(out, axis=int(ax), begin=int(b),
                               end=None if int(e) >= big or int(e) == 2147483647
                               else int(e))
    elif op == "Split":
        axis = int(a.get("axis", 0))
        n_out = len(node["output"])
        sections = a.get("split")
        if sections is None and len(node["input"]) > 1:
            sections = [int(x) for x in const_of(1)]
        if sections and len(set(int(s) for s in sections)) > 1:
            # uneven split: a chain of slice_axis, one per section
            bounds = _np.cumsum([0] + [int(s) for s in sections])
            out = [S.slice_axis(ins[0], axis=axis, begin=int(b),
                                end=int(e))
                   for b, e in zip(bounds[:-1], bounds[1:])]
        else:
            out = S.SliceChannel(ins[0], num_outputs=n_out, axis=axis,
                                 name=name)
    elif op == "Pad":
        if "pads" in a:
            pads = [int(x) for x in a["pads"]]
        else:
            pads = [int(x) for x in const_of(1)]
        mode = a.get("mode", "constant")
        if mode not in ("constant",):
            raise NotImplementedError("ONNX Pad mode %r" % mode)
        ndim = len(pads) // 2
        value = float(a.get("value", 0.0))
        if len(node["input"]) > 2 and const_of(2) is not None:
            value = float(_np.asarray(const_of(2)).ravel()[0])
        pad_width = []
        for i in range(ndim):
            pad_width += [pads[i], pads[ndim + i]]
        out = S.pad(ins[0], mode="constant", pad_width=tuple(pad_width),
                    constant_value=value, name=name)
    elif op == "Constant":
        arr = a.get("value")
        if arr is None:
            raise NotImplementedError("ONNX Constant without tensor value")
        consts[node["output"][0]] = arr
        out = S.Variable(node["output"][0])
    elif op in ("Upsample", "Resize"):
        mode = a.get("mode", "nearest")
        if mode != "nearest":
            raise NotImplementedError("ONNX %s mode %r" % (op, mode))
        # UpSampling maps output pixel i -> input floor(i/s).  For
        # integer scales that equals half_pixel with the round_prefer_*
        # rounding (ties never occur: (i+0.5)/s-0.5 is q+(r+0.5-s/2)/s
        # with the fraction strictly inside (-0.5, 0.5)) and asymmetric
        # with floor rounding.  Every other (coord, nearest_mode) pair
        # diverges for some integer scale (e.g. asymmetric +
        # round_prefer_floor at s=3 maps output 2 -> input 1, not 0) —
        # refuse rather than silently resample wrong.
        coord = a.get("coordinate_transformation_mode", "half_pixel")
        nearest = a.get("nearest_mode", "round_prefer_floor")
        ok = (coord == "half_pixel" and
              nearest in ("round_prefer_floor", "round_prefer_ceil")) or \
             (coord == "asymmetric" and nearest == "floor")
        if op == "Resize" and not ok:
            raise NotImplementedError(
                "ONNX Resize coordinate_transformation_mode %r with "
                "nearest_mode %r" % (coord, nearest))
        scales = a.get("scales")
        if scales is None:
            # Upsample (opset 9): input 1 is scales.  Resize: input 2 is
            # scales; input 3 would be `sizes`, which is NOT supported —
            # never read it as scales.
            idx = 1 if op == "Upsample" else 2
            c = const_of(idx) if len(node["input"]) > idx else None
            if c is not None and len(c):
                scales = [float(x) for x in c]
            elif op == "Resize" and len(node["input"]) > 3 and \
                    const_of(3) is not None and len(const_of(3)):
                raise NotImplementedError("ONNX Resize by `sizes`")
        if not scales or len(scales) < 4 or scales[2] != scales[3] \
                or scales[0] != 1.0 or scales[1] != 1.0:
            raise NotImplementedError("ONNX resize scales %r" % (scales,))
        if scales[2] != int(scales[2]):
            raise NotImplementedError(
                "ONNX resize: non-integer scale %r" % (scales[2],))
        out = S.UpSampling(ins[0], scale=int(scales[2]),
                           sample_type="nearest", name=name)
    else:
        raise NotImplementedError("ONNX import: op %r not supported" % op)

    outputs = node["output"]
    if len(outputs) == 1:
        env[outputs[0]] = out
    else:
        for i, oname in enumerate(outputs):
            if oname:
                env[oname] = out[i]


def import_graph(graph):
    """GraphProto dict -> (Symbol, arg_params, aux_params)."""
    from ... import symbol as S
    from ...ndarray import array

    consts = {t["name"]: tensor_to_numpy(t)
              for t in graph.get("initializer", [])}
    env = {}
    for vi in graph.get("input", []):
        name = vi["name"]
        env[name] = S.Variable(name)
    for cname in consts:
        if cname not in env:
            env[cname] = S.Variable(cname)
    for node in graph.get("node", []):
        _import_node(S, node, env, consts)
    outs = [env[o["name"]] for o in graph.get("output", [])]
    sym = outs[0] if len(outs) == 1 else S.Group(outs)

    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {}
    aux_params = {}
    for name, arr in consts.items():
        if name in aux_names:
            aux_params[name] = array(arr)
        elif name in arg_names:
            arg_params[name] = array(arr)
    return sym, arg_params, aux_params
