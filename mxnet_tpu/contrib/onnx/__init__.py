"""ONNX import/export (reference: python/mxnet/contrib/onnx/ —
onnx2mx.import_model, mx2onnx.export_model).

Self-contained: serialization uses a minimal protobuf wire codec
(_proto.py) instead of the onnx pip package, so it works in this image.
"""

from __future__ import annotations

import numpy as _np

from . import _proto
from .mx2onnx import export_symbol
from .onnx2mx import import_graph

__all__ = ["export_model", "import_model", "get_model_metadata"]


def export_model(sym, params, input_shape, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol (or saved files) to ONNX (reference:
    mx2onnx/export_model.py).  Returns the output path."""
    from ... import symbol as _s
    from ...ndarray import NDArray

    if isinstance(sym, str):
        sym = _s.load(sym)
    if isinstance(params, str):
        from ...ndarray import load as nd_load
        params = nd_load(params)
    np_params = {}
    for k, v in (params or {}).items():
        if k.startswith(("arg:", "aux:")):
            k = k[4:]
        np_params[k] = v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v)
    if input_shape and not isinstance(input_shape[0], (list, tuple)):
        input_shape = [input_shape]
    data_names = [n for n in sym.list_arguments() if n not in np_params]
    shapes = dict(zip(data_names, [tuple(s) for s in input_shape]))
    model = export_symbol(sym, np_params, shapes, input_dtype=input_type)
    payload = _proto.encode(model, "ModelProto")
    with open(onnx_file_path, "wb") as f:
        f.write(payload)
    return onnx_file_path


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference:
    onnx2mx/import_model.py)."""
    with open(model_file, "rb") as f:
        payload = f.read()
    model = _proto.decode(payload, "ModelProto")
    return import_graph(model["graph"])


def get_model_metadata(model_file):
    """Input/output descriptions of an ONNX model (reference:
    onnx2mx/import_model.py get_model_metadata)."""
    with open(model_file, "rb") as f:
        model = _proto.decode(f.read(), "ModelProto")
    graph = model["graph"]
    inits = {t["name"] for t in graph.get("initializer", [])}

    def _shape(vi):
        dims = vi.get("type", {}).get("tensor_type", {}) \
            .get("shape", {}).get("dim", [])
        return tuple(d.get("dim_value", 0) for d in dims)

    return {
        "input_tensor_data": [(vi["name"], _shape(vi))
                              for vi in graph.get("input", [])
                              if vi["name"] not in inits],
        "output_tensor_data": [(vi["name"], _shape(vi))
                               for vi in graph.get("output", [])],
    }
