"""Model quantization (INT8) with calibration.

Reference: python/mxnet/contrib/quantization.py (quantize_model,
_quantize_params, _quantize_symbol via the C++ quantize_graph_pass.cc,
calibration via _LayerOutputCollector / _get_optimal_thresholds) and
src/operator/quantization/.

TPU-native shape of the pass: instead of an nnvm rewrite producing long
int8 chains, each quantizable layer L(data, weight, bias) becomes

    quantize_v2(data) -> quantized_L (int32 accum on the MXU)
      -> requantize (calibrated range when available) -> dequantize

and everything else stays float32.  XLA fuses the dequantize into the
consumer, so the float hops between layers cost one multiply — the int8
matmul/conv (where the FLOPs are) is what matters.  Weights/biases are
quantized OFFLINE into the returned qarg_params (same `_quantize` /
`_quantize_min` / `_quantize_max` naming as the reference).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array
from ..symbol.passes import Pass as _Pass
from ..symbol.symbol import Symbol, _Node

__all__ = ["quantize_model", "quantize_net", "quantize_graph",
           "QuantizePass"]

# ops rewritten to int8 compute (reference pass quantizes conv/FC/pooling/
# flatten/concat; pooling & reshaping stay float here — they are
# bandwidth-bound, the MXU wins live in conv/FC)
_QUANTIZABLE = {"Convolution", "FullyConnected"}


def _real_range(arr):
    return float(max(abs(float(arr.min())), abs(float(arr.max())), 1e-30))


def _quantize_params(qsym, arg_params):
    """Offline-quantize weights/biases consumed by quantized nodes
    (reference: _quantize_params: <name>_quantize{,_min,_max})."""
    out = {}
    needed = set(qsym.list_arguments())
    for name, nd in arg_params.items():
        qname = name + "_quantize"
        if qname in needed:
            a = nd.asnumpy()
            real = _real_range(a)
            q = _np.clip(_np.rint(a * (127.0 / real)), -127, 127)
            out[qname] = array(q.astype(_np.int8))
            out[qname + "_min"] = array(_np.array([-real], _np.float32))
            out[qname + "_max"] = array(_np.array([real], _np.float32))
        if name in needed:
            out[name] = nd
    return out


class _GraphBuilder:
    """Rebuilds a Symbol DAG with quantized replacements node by node."""

    def __init__(self, th_dict, quantized_dtype):
        self.th_dict = th_dict or {}
        self.dtype = quantized_dtype
        self.mapping = {}  # id(old node) -> list of (new node, out_idx)
        self._vars = {}    # name -> variable node (shared weights stay shared)
        self._qcache = {}  # id(entry node), idx -> quantize_v2 entries

    def mapped(self, old_entry):
        node, idx = old_entry
        return self.mapping[id(node)][idx]

    def node(self, op, name, attrs, inputs, nout=1):
        from ..ops import registry as _reg
        if op is None:
            # one variable node per name: tied weights must resolve to ONE
            # argument slot, not N same-named duplicates
            n = self._vars.get(name)
            if n is None:
                n = _Node(None, name, attrs, [], nout)
                self._vars[name] = n
            return n
        canon = _reg.get(op).canonicalize_attrs(attrs)
        return _Node(op, name, canon, list(inputs), nout)

    def entry_name(self, entry):
        """Calibration key for a graph entry (original-graph names)."""
        node, idx = entry
        if node.op is None:
            return node.name
        if node.num_outputs > 1:
            return "%s_output%d" % (node.name, idx)
        return node.name + "_output"

    def quantize_entry(self, entry, key):
        """float entry -> (int8 entry, min entry, max entry).  One
        quantize_v2 per source tensor, shared by all consumers."""
        ck = (id(entry[0]), entry[1])
        cached = self._qcache.get(ck)
        if cached is not None:
            return cached
        attrs = {"out_type": "int8"}
        calib = self.th_dict.get(key)
        if calib is not None:
            attrs["min_calib_range"] = float(calib[0])
            attrs["max_calib_range"] = float(calib[1])
        n = self.node("_contrib_quantize_v2", key + "_quantize", attrs,
                      [entry], nout=3)
        out = ((n, 0), (n, 1), (n, 2))
        self._qcache[ck] = out
        return out

    def rewrite(self, node):
        """Return the replacement output entries for one original node."""
        if node.op is None:
            nn = self.node(None, node.name, {}, [])
            nn.attr_dict = node.attr_dict
            return [(nn, 0)]
        new_inputs = [self.mapped(e) for e in node.inputs]
        if node.op not in _QUANTIZABLE:
            nn = self.node(node.op, node.name, node.attrs, new_inputs,
                           node.num_outputs)
            nn.attr_dict = node.attr_dict
            return [(nn, i) for i in range(node.num_outputs)]
        return self.rewrite_quantized(node, new_inputs)

    def rewrite_quantized(self, node, new_inputs):
        name = node.name
        no_bias = bool(node.attrs.get("no_bias", False))
        data = new_inputs[0]
        # data: quantize dynamically or with calibrated range of the
        # tensor feeding this node
        dkey = self.entry_name(node.inputs[0])
        qdata, dmin, dmax = self.quantize_entry(data, dkey)
        # weights: offline-quantized parameter variables, named after the
        # ORIGINAL weight/bias variables (reference _quantize_params naming)
        wname = node.inputs[1][0].name
        if not node.inputs[1][0].is_variable:
            raise MXNetError("quantization requires %s's weight to be a "
                             "variable" % name)
        wvar = self.node(None, wname + "_quantize", {}, [])
        wmin = self.node(None, wname + "_quantize_min", {}, [])
        wmax = self.node(None, wname + "_quantize_max", {}, [])
        ins = [qdata, (wvar, 0)]
        if no_bias:
            # keep arity: quantized op signature has bias slots; pass weight
            # range scalars twice and flag no_bias
            bvar = bmin = bmax = None
        else:
            bname = node.inputs[2][0].name
            bvar = self.node(None, bname + "_quantize", {}, [])
            bmin = self.node(None, bname + "_quantize_min", {}, [])
            bmax = self.node(None, bname + "_quantize_max", {}, [])
        qop = ("_contrib_quantized_conv" if node.op == "Convolution"
               else "_contrib_quantized_fully_connected")
        attrs = dict(node.attrs)
        if no_bias:
            ins = ins + [(wvar, 0)]  # dummy bias slot (unused under no_bias)
            ins += [dmin, dmax, (wmin, 0), (wmax, 0), (wmin, 0), (wmax, 0)]
        else:
            ins = ins + [(bvar, 0)]
            ins += [dmin, dmax, (wmin, 0), (wmax, 0), (bmin, 0), (bmax, 0)]
        qnode = self.node(qop, name + "_quantize", attrs, ins, nout=3)
        # requantize int32 -> int8, calibrated by this layer's output range
        rattrs = {}
        okey = name + "_output"
        calib = self.th_dict.get(okey)
        if calib is not None:
            rattrs = {"min_calib_range": float(calib[0]),
                      "max_calib_range": float(calib[1])}
        rnode = self.node("_contrib_requantize", name + "_requantize", rattrs,
                          [(qnode, 0), (qnode, 1), (qnode, 2)], nout=3)
        dq = self.node("_contrib_dequantize", name + "_dequantize", {},
                       [(rnode, 0), (rnode, 1), (rnode, 2)])
        return [(dq, 0)]


def _quantize_impl(sym, excluded_sym_names=(), th_dict=None,
                   quantized_dtype="int8"):
    """The int8 rewrite itself: quantizable layers -> int8 compute
    subgraphs.  Public entry is :func:`quantize_graph`, which routes
    through the symbol pass manager."""
    excluded = set(excluded_sym_names or ())
    gb = _GraphBuilder(th_dict, quantized_dtype)
    for node in sym._topo_nodes():
        if node.op in _QUANTIZABLE and node.name in excluded:
            new_inputs = [gb.mapped(e) for e in node.inputs]
            nn = gb.node(node.op, node.name, node.attrs, new_inputs,
                         node.num_outputs)
            nn.attr_dict = node.attr_dict
            gb.mapping[id(node)] = [(nn, i) for i in range(node.num_outputs)]
        else:
            gb.mapping[id(node)] = gb.rewrite(node)
    return Symbol([gb.mapped(e) for e in sym._outputs])


class QuantizePass(_Pass):
    """Pass-manager wrapper around :func:`_quantize_impl`: the rewrite
    is unchanged, but its output is re-verified (structure, registry
    arity, cache-key soundness, partial shape/dtype interpretation)
    before the quantized graph reaches any executor."""

    name = "quantize"

    def __init__(self, excluded_sym_names=(), th_dict=None,
                 quantized_dtype="int8"):
        self._excluded = tuple(excluded_sym_names or ())
        self._th_dict = th_dict
        self._dtype = quantized_dtype

    def run(self, sym, ctx):
        return _quantize_impl(sym, self._excluded, self._th_dict,
                              self._dtype)


def quantize_graph(sym, excluded_sym_names=(), th_dict=None,
                   quantized_dtype="int8", ctx=None):
    """Rewrite a Symbol: quantizable layers -> int8 compute subgraphs,
    verified by the pass manager before it is returned."""
    from ..symbol.passes import PassContext

    return QuantizePass(excluded_sym_names, th_dict, quantized_dtype)(
        sym, ctx or PassContext())


# ------------------------------------------------------------ calibration

def _collect_layer_stats(sym, arg_params, aux_params, calib_data,
                         data_names, label_names, num_calib_examples, keys):
    """Run fp32 forward over calib batches; return {key: list of np arrays}
    for every internal output named in `keys` (reference:
    _LayerOutputCollector via set_monitor_callback)."""
    internals = sym.get_internals()
    wanted = [n for n in internals.list_outputs() if n in keys]
    from ..symbol.symbol import Group
    group = Group([internals[n] for n in wanted])

    collected = {k: [] for k in wanted}
    calib_data.reset()
    seen = 0
    exe = None
    group_args = set(group.list_arguments())
    for batch in calib_data:
        feeds = {}
        for dn, d in zip(data_names, batch.data):
            feeds[dn] = d
        for ln, l in zip(label_names, batch.label or []):
            feeds[ln] = l
        feeds = {k: v for k, v in feeds.items() if k in group_args}
        if exe is None:
            # ONE executor reused across batches — jit compiles once
            args = dict(arg_params)
            args.update(feeds)
            exe = _make_eval_executor(group, args, aux_params)
        outs = exe.forward(is_train=False, **feeds)
        for k, o in zip(wanted, outs):
            collected[k].append(o.asnumpy())
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return collected


def _make_eval_executor(sym, args, aux_params):
    """Inference-only Executor over a dict of NDArray inputs."""
    from ..executor import Executor

    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    missing = [n for n in arg_names if n not in args]
    if missing:
        raise MXNetError("calibration: missing inputs %s" % missing)
    return Executor(sym, None, [args[n] for n in arg_names], {},
                    {n: "null" for n in arg_names},
                    [(aux_params or {})[n] for n in aux_names])


def _naive_th(collected):
    return {k: (min(float(a.min()) for a in v),
                max(float(a.max()) for a in v))
            for k, v in collected.items() if v}


def _smooth_distribution(p, eps=0.0001):
    """Move a little mass onto zero bins so KL is finite (the standard
    smoothing from the KL-calibration literature; reference:
    contrib/quantization.py _smooth_distribution)."""
    is_zeros = p == 0
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        return None
    eps1 = eps * float(n_zeros) / float(n_nonzeros)
    hist = p.astype(_np.float64).copy()
    hist[is_zeros] = eps
    hist[~is_zeros] -= eps1 * hist[~is_zeros]
    return hist


def _kl_divergence(p, q):
    p = p / p.sum()
    q = q / q.sum()
    mask = p > 0
    return float(_np.sum(p[mask] * _np.log(p[mask] / q[mask])))


def _optimal_threshold_kl(arr, num_bins=1001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| for int8 (the histogram search of
    the KL-calibration method; reference: _get_optimal_threshold).

    For each candidate truncation point i, compare the clipped reference
    distribution P (outliers folded into the edge bin) against its
    255-bin-quantized reconstruction Q; keep the i minimizing KL(P||Q)."""
    a = _np.abs(_np.concatenate([x.ravel() for x in arr]))
    amax = float(a.max()) if a.size else 0.0
    if amax < 1e-8:
        return 1e-8
    hist, edges = _np.histogram(a, bins=num_bins, range=(0, amax))
    hist = hist.astype(_np.float64)
    best_div, best_t = _np.inf, amax
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 128)):
        p = hist[:i].copy()
        if p.sum() == 0:
            continue
        p[-1] += hist[i:].sum()  # fold outliers into the edge bin
        # build Q: collapse the i bins into 255 quantized levels, then
        # expand back to i bins spreading each level over its nonzero bins
        sliced = hist[:i]
        factor = i / float(num_quantized_bins)
        q = _np.zeros(i, dtype=_np.float64)
        for j in range(num_quantized_bins):
            lo = int(_np.floor(j * factor))
            hi = min(int(_np.ceil((j + 1) * factor)), i)
            chunk = sliced[lo:hi]
            nz = chunk != 0
            cnt = int(nz.sum())
            if cnt:
                q[lo:hi][nz] = chunk[nz].sum() / cnt
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        if ps is None or qs is None:
            continue
        div = _kl_divergence(ps, qs)
        if div < best_div:
            best_div = div
            best_t = float(edges[i]) if i < len(edges) else amax
    return max(best_t, amax * 1e-3)


def _entropy_th(collected):
    th = {}
    for k, v in collected.items():
        if not v:
            continue
        t = _optimal_threshold_kl(v)
        th[k] = (-t, t)
    return th


def _calib_keys(sym, excluded):
    """Names whose ranges calibration must provide: inputs to and outputs
    of every quantizable node."""
    keys = set()
    gb = _GraphBuilder({}, "int8")
    for node in sym._topo_nodes():
        if node.op in _QUANTIZABLE and node.name not in excluded:
            keys.add(gb.entry_name(node.inputs[0]))
            keys.add(node.name + "_output")
    return keys


# ------------------------------------------------------------- public API

def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None):
    """reference: contrib/quantization.py quantize_model.

    Returns (qsym, qarg_params, aux_params)."""
    if quantized_dtype not in ("int8", "auto"):
        raise NotImplementedError(
            "quantized_dtype=%r: this build quantizes to int8 (symmetric, "
            "MXU-native); uint8 affine compute is not implemented"
            % (quantized_dtype,))
    excluded = set(excluded_sym_names or ())
    th_dict = {}
    if calib_mode and calib_mode != "none":
        if calib_data is None:
            raise ValueError("calib_mode=%r requires calib_data" % calib_mode)
        keys = _calib_keys(sym, excluded)
        collected = _collect_layer_stats(
            sym, arg_params, aux_params, calib_data, list(data_names),
            list(label_names), num_calib_examples, keys)
        if calib_mode == "naive":
            th_dict = _naive_th(collected)
        elif calib_mode == "entropy":
            th_dict = _entropy_th(collected)
        else:
            raise ValueError("unknown calib_mode %r" % calib_mode)
    qsym = quantize_graph(sym, excluded, th_dict, quantized_dtype)
    qarg_params = _quantize_params(qsym, arg_params)
    return qsym, qarg_params, dict(aux_params or {})


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 calib_data=None, calib_mode="none", num_calib_examples=None,
                 data_shapes=None, ctx=None, logger=None):
    """Quantize a Gluon HybridBlock -> SymbolBlock (reference:
    contrib/quantization.py quantize_net)."""
    from .. import symbol as _sym_mod
    from ..gluon.block import SymbolBlock

    if data_shapes is None:
        if calib_data is None:
            raise ValueError("need data_shapes or calib_data")
        batch = next(iter(calib_data))
        data_shapes = [d.shape for d in batch.data]
        calib_data.reset()
    data_syms = [_sym_mod.var("data%d" % i if i else "data")
                 for i in range(len(data_shapes))]
    sym, params = _trace_block(network, data_syms, data_shapes)
    arg_params = {k: v for k, v in params.items()}
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, {}, data_names=[s.name for s in data_syms],
        excluded_sym_names=exclude_layers, calib_mode=calib_mode,
        calib_data=calib_data, num_calib_examples=num_calib_examples,
        quantized_dtype=quantized_dtype)
    all_params = dict(qarg)
    all_params.update(qaux)
    return SymbolBlock(qsym, data_syms, params=all_params)


def _trace_block(network, data_syms, data_shapes):
    """Trace a HybridBlock into (Symbol, params-dict)."""
    import numpy as np

    from ..ndarray import zeros

    # make sure params are materialized
    args = [zeros(s) for s in data_shapes]
    network(*args)
    sym = network(*data_syms)
    if isinstance(sym, (list, tuple)):
        from ..symbol.symbol import Group
        sym = Group(list(sym))
    params = {}
    for name, p in network.collect_params().items():
        params[name] = p.data()
    return sym, params
