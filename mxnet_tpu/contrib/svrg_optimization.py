"""SVRG (stochastic variance-reduced gradient) optimization (reference:
python/mxnet/contrib/svrg_optimization/{svrg_module,svrg_optimizer}.py).

SVRGModule wraps Module: every ``update_freq`` epochs it snapshots the
parameters and computes the FULL-dataset gradient at the snapshot; each
step then uses the variance-reduced gradient
``g_i(w) - g_i(w_snap) + g_full(w_snap)``.

TPU-native note: each of the three gradient terms is the same jitted
fwd/bwd computation — the control variate is plain array arithmetic
between executions, so everything stays on device.
"""

from __future__ import annotations

import numpy as _np

from ..module.module import Module
from ..ndarray import NDArray, array, zeros

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """reference: svrg_module.py SVRGModule."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = int(update_freq)
        self._param_snapshot = None   # {name: NDArray} at snapshot
        self._full_grads = None       # {name: NDArray} full grad at snapshot

    # -------------------------------------------------------- snapshot
    def update_full_grads(self, train_data):
        """Snapshot params and accumulate the full-dataset gradient at the
        snapshot (reference: svrg_module.update_full_grads)."""
        arg_params, _ = self.get_params()
        self._param_snapshot = {k: v.copy() for k, v in arg_params.items()}
        sums = {k: zeros(v.shape) for k, v in arg_params.items()}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self.forward_backward(batch)
            for name, grads in zip(self._exec_group.param_names,
                                   self._exec_group.grad_arrays):
                if grads and grads[0] is not None:
                    sums[name] += grads[0]
            nbatch += 1
        train_data.reset()
        self._full_grads = {k: v / max(nbatch, 1) for k, v in sums.items()}

    def _snapshot_batch_grad(self, data_batch):
        """Gradient of the CURRENT batch at the SNAPSHOT parameters."""
        cur_ref, aux = self.get_params()
        # deep copy: set_params writes THROUGH the cache objects
        # get_params returns, so a reference would alias the snapshot
        current = {k: v.copy() for k, v in cur_ref.items()}
        self.set_params(self._param_snapshot, aux,
                        allow_missing=False, force_init=True)
        self.forward_backward(data_batch)
        snap_grads = {
            name: grads[0].copy()
            for name, grads in zip(self._exec_group.param_names,
                                   self._exec_group.grad_arrays)
            if grads and grads[0] is not None}
        self.set_params(current, aux, allow_missing=False, force_init=True)
        return snap_grads

    def update_svrg(self, data_batch):
        """One variance-reduced step: fwd/bwd at w and at w_snap, combine,
        then the normal optimizer update."""
        assert self._full_grads is not None, "call update_full_grads first"
        snap_grads = self._snapshot_batch_grad(data_batch)
        self.forward_backward(data_batch)
        for name, grads in zip(self._exec_group.param_names,
                               self._exec_group.grad_arrays):
            if not grads or grads[0] is None:
                continue
            g = grads[0]
            vr = g - snap_grads[name] + self._full_grads[name]
            g._assign(vr._data)
        self.update()

    # ------------------------------------------------------------- fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            num_epoch=None, optimizer="sgd", optimizer_params=None,
            begin_epoch=0, initializer=None, epoch_end_callback=None,
            batch_end_callback=None, validation_metric=None, **kwargs):
        """Training loop with periodic full-gradient refresh
        (reference: svrg_module.fit)."""
        from .. import metric as _metric
        from ..module.base_module import BatchEndParam, _as_list

        if kwargs:
            raise TypeError("SVRGModule.fit: unsupported arguments %s"
                            % sorted(kwargs))
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        if not self.params_initialized:
            self.init_params(initializer=initializer)
        self.init_optimizer(optimizer=optimizer,
                            optimizer_params=optimizer_params or
                            {"learning_rate": 0.01})
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        for epoch in range(begin_epoch, num_epoch or 1):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.update_svrg(batch)
                self.update_metric(eval_metric, batch.label)
                for cb in _as_list(batch_end_callback or []):
                    cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=eval_metric,
                                     locals=locals()))
            if eval_data is not None:
                vm = validation_metric or eval_metric
                self.score(eval_data, vm)
            for cb in _as_list(epoch_end_callback or []):
                arg_params, aux_params = self.get_params()
                cb(epoch, self.symbol, arg_params, aux_params)
        return self
