"""TensorBoard metric logging (reference: contrib/tensorboard.py).

The reference delegates to the external ``mxboard`` package; this
container is zero-egress, so a self-contained writer emits the
TFRecord/tfevents wire format directly, reusing the schema-driven
protobuf codec from ``contrib/onnx/_proto.py``.  Files are readable by
standard TensorBoard: ``tensorboard --logdir=<logging_dir>``.
"""

from __future__ import annotations

import os
import socket
import struct
import time

from .onnx import _proto

__all__ = ["LogMetricsCallback", "SummaryWriter"]

# Event/Summary wire schemas (public tensorflow event.proto /
# summary.proto field numbers), registered alongside the ONNX tables
_proto.SCHEMAS.setdefault("TBSummaryValue", {
    1: ("tag", "str"),
    2: ("simple_value", "float"),
})
_proto.SCHEMAS.setdefault("TBSummary", {
    1: ("value", "msg:TBSummaryValue*"),
})
_proto.SCHEMAS.setdefault("TBEvent", {
    1: ("wall_time", "double"),
    2: ("step", "varint"),
    3: ("file_version", "str"),
    5: ("summary", "msg:TBSummary"),
})


# ------------------------------------------------------------- crc32c -----
def _crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC_TABLE = _crc32c_table()


def _crc32c(data):
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


_WRITER_SEQ = 0


class SummaryWriter:
    """Append-only tfevents writer (the mxboard subset the reference
    callback uses: ``add_scalar``)."""

    def __init__(self, logging_dir):
        global _WRITER_SEQ

        os.makedirs(logging_dir, exist_ok=True)
        _WRITER_SEQ += 1
        # hostname+pid+seq keeps concurrent writers in one logdir apart
        fname = "events.out.tfevents.%d.%s.%d.%d" % (
            int(time.time()), socket.gethostname(), os.getpid(),
            _WRITER_SEQ)
        self._path = os.path.join(logging_dir, fname)
        self._f = open(self._path, "ab")
        # standard tfevents header: v2 purge semantics for readers
        self._write_event({"wall_time": time.time(), "step": 0,
                           "file_version": "brain.Event:2"})

    def _write_event(self, event_dict):
        ev = _proto.encode(event_dict, "TBEvent")
        header = struct.pack("<Q", len(ev))
        self._f.write(header + struct.pack("<I", _masked_crc(header)))
        self._f.write(ev + struct.pack("<I", _masked_crc(ev)))
        self._f.flush()

    def add_scalar(self, tag, value, global_step=0):
        self._write_event({
            "wall_time": time.time(),
            "step": int(global_step),
            "summary": {"value": [{"tag": tag,
                                   "simple_value": float(value)}]},
        })

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Batch/epoch-end callback writing every metric as a TensorBoard
    scalar (reference: contrib/tensorboard.py LogMetricsCallback).

    Steps are a monotonic per-callback counter so batch-end usage plots
    within-epoch progress instead of stacking a whole epoch at one x
    value (mxboard's own global_step default)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value,
                                           global_step=self._step)


def read_events(path):
    """Parse a tfevents file back into a list of Event dicts — the
    verification twin of the writer (and a debugging aid)."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (n,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError("corrupt tfevents header")
            data = f.read(n)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise ValueError("corrupt tfevents record")
            out.append(_proto.decode(data, "TBEvent"))
    return out
