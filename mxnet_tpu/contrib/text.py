"""Text utilities: vocabulary + token embeddings (reference:
python/mxnet/contrib/text/{vocab,embedding,utils}.py).

Zero-egress container: pretrained GloVe/fastText downloads are gated
behind CustomEmbedding (load from a local file) — the composition APIs
(indexing, get_vecs_by_tokens, attaching to gluon.nn.Embedding) match
the reference.
"""

from __future__ import annotations

import collections

import numpy as _np

from ..ndarray import NDArray, array

__all__ = ["Vocabulary", "CustomEmbedding", "count_tokens_from_str",
           "register", "create"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """reference: text/utils.py count_tokens_from_str."""
    source_str = source_str.lower() if to_lower else source_str
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in source_str.split(seq_delim):
        counter.update(t for t in seq.split(token_delim) if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with reserved tokens (reference:
    text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        assert unknown_token not in reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        self._reserved_tokens = reserved_tokens or None
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        if isinstance(tokens, str):
            return self._token_to_idx.get(tokens, 0)
        return [self._token_to_idx.get(t, 0) for t in tokens]

    def to_tokens(self, indices):
        if isinstance(indices, int):
            return self._idx_to_token[indices]
        return [self._idx_to_token[i] for i in indices]


class _TokenEmbedding(Vocabulary):
    """Base token embedding (reference: text/embedding.py
    _TokenEmbedding): vocabulary + an (N, D) vector table."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = self._idx_to_vec.asnumpy()[idx]
        out = array(vecs)
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        if isinstance(tokens, str):
            tokens = [tokens]
        vecs = _np.array(self._idx_to_vec.asnumpy())  # writable copy
        newv = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else _np.asarray(new_vectors)
        newv = newv.reshape(len(tokens), -1)
        for t, v in zip(tokens, newv):
            if t not in self._token_to_idx:
                raise ValueError("token %r not in the embedding" % t)
            vecs[self._token_to_idx[t]] = v
        self._idx_to_vec = array(vecs)


class CustomEmbedding(_TokenEmbedding):
    """Embedding loaded from a local text file of
    '<token> <v0> <v1> ...' lines (reference: text/embedding.py
    CustomEmbedding — the no-download path)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        tokens = []
        vecs = []
        with open(pretrained_file_path, encoding=encoding) as f:
            for lineno, line in enumerate(f, 1):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                if lineno == 1 and len(parts) == 2 and \
                        all(p.isdigit() for p in parts):
                    continue  # fastText/word2vec '<count> <dim>' header
                vec = [float(x) for x in parts[1:]]
                if vecs and len(vec) != self._vec_len:
                    raise ValueError(
                        "%s:%d: vector has %d dims, expected %d"
                        % (pretrained_file_path, lineno, len(vec),
                           self._vec_len))
                if not vecs:
                    self._vec_len = len(vec)
                tokens.append(parts[0])
                vecs.append(vec)
        if vocabulary is not None:
            keep = [(t, v) for t, v in zip(tokens, vecs)
                    if t in vocabulary.token_to_idx]
        else:
            keep = list(zip(tokens, vecs))
        # zero rows for <unk> AND any reserved tokens already in the
        # vocabulary, keeping idx_to_vec aligned with idx_to_token
        table = [_np.zeros(self._vec_len, _np.float32)
                 for _ in self._idx_to_token]
        for t, v in keep:
            if t in self._token_to_idx:
                table[self._token_to_idx[t]] = _np.asarray(v, _np.float32)
                continue
            self._token_to_idx[t] = len(self._idx_to_token)
            self._idx_to_token.append(t)
            table.append(_np.asarray(v, _np.float32))
        self._idx_to_vec = array(_np.stack(table))


_EMBED_REGISTRY = {"CustomEmbedding": CustomEmbedding}


def register(cls):
    """reference: embedding.register."""
    _EMBED_REGISTRY[cls.__name__] = cls
    return cls


def create(embedding_name, **kwargs):
    """reference: embedding.create."""
    if embedding_name not in _EMBED_REGISTRY:
        raise KeyError(
            "unknown embedding %r (pretrained downloads are unavailable in "
            "this environment; use CustomEmbedding with a local file)"
            % embedding_name)
    return _EMBED_REGISTRY[embedding_name](**kwargs)
