"""Live metrics timeline — per-step time series, scrapeable export.

Every observability layer so far is post-mortem: ``runtime_stats``
counters are cumulative, diag dumps land at atexit/SIGUSR1, traces
cover one run.  A long production run needs *what is happening now and
how it is trending*: memory creeping up, throughput decaying, a
straggler emerging at step 40k — the continuous-monitoring shape of
every serving stack, and what the ZeRO-style runs of arXiv:2004.13336
watch their push-RTT skew with.  This module is that layer:

- a **bounded ring** of per-step samples, captured guard-first at the
  ``gluon.Trainer.step`` seam (disabled: one dict read, bench-gated in
  ``tests/test_bench_gate.py``).  Each sample folds the other layers'
  state into one host-side dict: step wall time + the ``stepstats``
  phase window, throughput (samples/s), **windowed deltas** of the
  cumulative compile/miss/fallback/kv-retry/dedup counters (so rates,
  not lifetime totals), live/peak device bytes (``device_memory``),
  jit-cache size, per-series kv push/pull-RTT window p50/p99
  (bucket-delta over ``histogram``), and the health layer's latest
  grad-norm / NaN flags (ring read only — never drains).
- a **JSONL appender** (``MXNET_TPU_METRICS=<file>``): every
  ``MXNET_TPU_METRICS_INTERVAL`` steps (default 1) the newest sample is
  appended as one ``write()`` of a full line, so a tailing reader /
  dashboard never sees a torn record.  ``tools/launch.py`` rank-suffixes
  the path per spawned process; a multi-rank run *without* launch.py
  self-suffixes from ``log.process_identity()`` (non-zero ranks and
  servers) instead of silently clobbering rank 0's file.  Render with
  ``python -m mxnet_tpu.runtime_stats metrics.jsonl`` or
  ``python tools/diagnose.py --timeline metrics.jsonl``.
- a **read-only Prometheus endpoint** (``MXNET_TPU_METRICS_PORT=<p>``):
  a daemon thread serves ``/metrics`` in Prometheus text format —
  counters, gauges, and latency summaries — built from snapshots only.
  It never drains health queues and never touches the device, so the
  compute path stays host-sync-free (the mxlint callgraph rule).

The trend doctor (``perfdoctor.diagnose(timeline=...)`` /
``tools/diagnose.py --doctor``) reads the same series — from this ring,
a JSONL file, or a diag dump (``runtime_stats.diag_snapshot`` embeds
the ring) — and ranks leaks, throughput decay, step-time spikes, and
kv-RTT drift like any other finding.

Environment variables
---------------------
``MXNET_TPU_METRICS``           JSONL destination; enables the timeline.
``MXNET_TPU_METRICS_PORT``      port for the ``/metrics`` endpoint;
    enables the timeline.  One process per port — give each rank its
    own, or rely on the JSONL export for multi-rank runs.
``MXNET_TPU_METRICS_HOST``      bind address for the endpoint (default
    all interfaces; set ``127.0.0.1`` for loopback-only).
``MXNET_TPU_METRICS_INTERVAL``  steps between JSONL appends (default 1;
    the in-memory ring samples every step regardless).
Unset, the timeline auto-enables under ``MXNET_TPU_PROFILE`` /
``MXNET_TPU_DIAG`` (ring only — those runs already pay for telemetry,
and their diag dump should carry a populated timeline).

Docs: docs/OBSERVABILITY.md "Live metrics & trends".
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time

from . import device_memory as _dm
from . import histogram as _histogram
from . import stepstats as _stepstats
from .log import (get_logger, process_identity, rank_suffix_path,
                  warn_rate_limited)

__all__ = ["enable", "disable", "is_enabled", "on_step", "samples",
           "timeline", "snapshot", "serve", "stop_server",
           "prometheus_text", "parse_jsonl", "load", "render", "reset",
           "RING_DEFAULT"]

RING_DEFAULT = 1024

# kv-RTT series sampled as windowed percentiles (aggregate + per shard)
_KV_PREFIXES = ("kv:push_rtt", "kv:pull_rtt")

_state = {"on": False}
_ring: collections.deque = collections.deque(maxlen=RING_DEFAULT)
# per-run mutable config/clock state; all mutation is GIL-atomic dict
# arithmetic on the training thread (the runtime_stats contract)
# mxlint: disable=thread-shared-state -- single-writer by contract: on_step runs on the training thread; other roots only read snapshots
_cur = {"boundary": None, "step": 0, "interval": 1,
        "path": None, "writer": None, "abs_path": None,
        # cumulative-counter baselines for the windowed deltas
        "prev": None, "prev_hist": {}}
_agg = {"samples": 0, "written": 0}
_server: list = []            # [ThreadingHTTPServer] while serving

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.metrics_timeline"))
    return _logger_cache[0]


# ------------------------------------------------------------ lifecycle


def enable(path=None, port=None, interval=None, ring=None):
    """Turn the timeline on: re-arm the sample ring, optionally attach
    the JSONL appender (``path``) and the ``/metrics`` endpoint
    (``port``; 0 picks a free port — read it back from the returned
    state via :func:`server_port`).  Also raises the cheap host-side
    layers the samples read from — ``stepstats`` and ``histogram`` —
    unless their env vars force them off."""
    global _ring
    _ring = collections.deque(maxlen=int(ring or RING_DEFAULT))
    if interval is None:
        try:
            interval = int(os.environ.get(
                "MXNET_TPU_METRICS_INTERVAL", "1"))
        except ValueError:
            interval = 1
    _cur.update({"boundary": None, "step": 0,
                 "interval": max(1, int(interval)),
                 "path": path, "prev": None, "prev_hist": {}})
    _close_writer()
    _agg["samples"] = 0
    _agg["written"] = 0
    # a timeline without phase/latency feeds is just wall times: raise
    # the pure-host layers it samples (both are dict arithmetic; an
    # explicit MXNET_TPU_STEPSTATS=0 / MXNET_TPU_HISTOGRAMS=0 wins)
    if os.environ.get("MXNET_TPU_STEPSTATS") != "0":
        _stepstats.enable()
    if os.environ.get("MXNET_TPU_HISTOGRAMS") != "0":
        _histogram.enable()
    _state["on"] = True
    if port is not None:
        serve(port)
    return _cur


def disable():
    """Stop sampling (the ring stays readable; ``reset()`` drops it).
    The JSONL writer is flushed+closed and the endpoint shut down."""
    _state["on"] = False
    _close_writer()
    stop_server()


def is_enabled():
    return _state["on"]


def reset():
    """Drop every sample/baseline and re-open the warmup window
    (tests); keeps the enabled flag, writer path, and server as-is."""
    _ring.clear()
    _cur.update({"boundary": None, "step": 0, "prev": None,
                 "prev_hist": {}})
    _agg["samples"] = 0


def _close_writer():
    w = _cur["writer"]
    _cur["writer"] = None
    _cur["abs_path"] = None
    if w is not None:
        try:
            w.close()
        except OSError:
            pass


# ------------------------------------------------------------- sampling


def on_step(batch_size=None):
    """One training-step boundary (called by ``gluon.Trainer.step``
    after the stepstats window closes, so the sample carries this
    step's phase breakdown).  The first boundary only arms the clock —
    the warmup window (imports, first compiles) is discarded, and the
    cumulative-counter baselines are primed so the first real sample's
    deltas cover exactly one step.  Callers guard on ``_state["on"]``;
    this re-check makes a mid-step disable safe."""
    if not _state["on"]:
        return
    now = time.perf_counter()
    boundary = _cur["boundary"]
    _cur["boundary"] = now
    _cur["step"] += 1
    if boundary is None:
        _cur["prev"] = _cum_totals()
        _cur["prev_hist"] = _hist_baseline()
        return
    sample = _build_sample(now - boundary, batch_size)
    _ring.append(sample)
    _agg["samples"] += 1
    if _cur["path"] and _cur["step"] % _cur["interval"] == 0:
        _write_jsonl(sample)


def _cum_totals():
    """Cheap cumulative totals the windowed deltas are cut from —
    O(ops) dict reads, same budget as ``runtime_stats.health_probe``
    (which runs per drained step); no cost aggregation, no snapshot."""
    from . import runtime_stats as _rts

    misses = fallbacks = 0
    for s in list(_rts._PER_OP.values()):
        misses += s["misses"]
        fallbacks += s["fallbacks"]
    compiles = 0
    for st in list(_rts._STORM.values()):
        compiles += st["compiles"]
    c = _rts._COUNTERS
    return {"compiles": compiles, "misses": misses,
            "fallbacks": fallbacks,
            "kv_retries": c.get("kvstore_retries", 0),
            "kv_dedup": c.get("kvstore_dup_suppressed", 0),
            # whole-step-program calls (compiled_step.py): keeps the
            # windows coherent when per-op warm-dispatch deltas
            # collapse to ~1 call/step — a sample showing zero misses
            # and compiled_steps=1 reads as "fused", not "idle"
            "compiled_steps": c.get("compiled_step_steps", 0),
            # ZeRO weight-update sharding collective traffic
            # (parallel/gluon_step.py): per-window byte deltas make
            # all-gather growth visible in the same timeline the
            # perfdoctor trend rules read
            "zero_steps": c.get("zero_steps", 0),
            "zero_allgather_bytes": c.get("zero_allgather_bytes", 0),
            "zero_reduce_bytes": c.get("zero_reduce_bytes", 0)}


def _jit_cache_size():
    """Total jit-cache entries across the op registry (read-side dict
    ``len()`` per op, never a dispatch)."""
    from .ops import registry as _registry

    total = 0
    seen = set()
    for op in list(_registry._OP_REGISTRY.values()):
        if id(op) in seen:
            continue
        seen.add(id(op))
        total += len(op._jit_cache)
    return total


def _hist_baseline():
    """Bucket-level snapshot of every kv-RTT histogram, for the
    windowed-percentile delta."""
    out = {}
    for name, h in list(_histogram._HISTS.items()):
        if name.startswith(_KV_PREFIXES):
            out[name] = (dict(h.buckets), h.count, h.total)
    return out


def _hist_windows():
    """Windowed p50/p99 per kv-RTT series: the bucket counts that
    arrived since the previous step boundary, percentile-interpolated
    over the delta histogram (within one log2 bucket of the true order
    statistic — the ``histogram.py`` contract, minus the exact-min/max
    tightening a window cannot keep)."""
    prev = _cur["prev_hist"]
    new_prev = {}
    out = {}
    for name, h in list(_histogram._HISTS.items()):
        if not name.startswith(_KV_PREFIXES):
            continue
        buckets = dict(h.buckets)
        count, total = h.count, h.total
        new_prev[name] = (buckets, count, total)
        p = prev.get(name)
        if p:
            pb, pc, pt = p
            dbuckets = {b: c - pb.get(b, 0) for b, c in buckets.items()
                        if c - pb.get(b, 0) > 0}
            dcount, dtotal = count - pc, total - pt
        else:
            dbuckets, dcount, dtotal = buckets, count, total
        if dcount <= 0 or not dbuckets:
            continue
        wh = _histogram.Histogram()
        wh.buckets = dbuckets
        wh.count = dcount
        wh.total = max(0.0, dtotal)
        bs = sorted(dbuckets)
        wh.min = _histogram.bucket_bounds(bs[0])[0]
        wh.max = _histogram.bucket_bounds(bs[-1])[1]
        out[name] = {"count": dcount,
                     "mean_ms": wh.total / dcount * 1e3,
                     "p50_ms": wh.percentile(50) * 1e3,
                     "p99_ms": wh.percentile(99) * 1e3}
    _cur["prev_hist"] = new_prev
    return out


def _health_flags():
    """Latest flight-ring record's grad-norm / non-finite flags — a
    plain host read of already-drained values; NEVER drains the
    monitor's pending device queue (the health-layer contract)."""
    from . import health as _health

    mon = _health._GLOBAL[0] if _health._state["on"] and _health._GLOBAL \
        else None
    if mon is None:
        return None
    ring = mon.flight._ring
    if not ring:
        return None
    rec = ring[-1]
    out = {"nan": 1 if rec.get("nan_total") else 0,
           "inf": 1 if rec.get("inf_total") else 0}
    if rec.get("grad_norm") is not None:
        out["grad_norm"] = rec["grad_norm"]
    return out


def _build_sample(wall, batch_size):
    sample = {"t": time.time(), "step": _cur["step"],
              "wall_ms": wall * 1e3}
    if batch_size and wall > 0:
        sample["throughput"] = batch_size / wall
    if _stepstats._state["on"]:
        last = _stepstats._agg["last"]
        if last is not None:
            sample["phases_ms"] = {k: v * 1e3 for k, v in last.items()
                                   if k != "wall"}
    cum = _cum_totals()
    prev = _cur["prev"] or {}
    for k, v in cum.items():
        d = v - prev.get(k, 0)
        if d:
            sample[k] = d
    _cur["prev"] = cum
    live, peak = _dm.live_totals()
    sample["live_bytes"] = live
    sample["peak_bytes"] = peak
    sample["jit_entries"] = _jit_cache_size()
    kv = _hist_windows()
    if kv:
        sample["kv_rtt_ms"] = kv
    h = _health_flags()
    if h:
        sample.update(h)
    return sample


def _write_jsonl(sample):
    w = _cur["writer"]
    if w is None:
        # lazy open in append mode; the path self-suffixes with this
        # process's role+rank when running multi-process without
        # launch.py's env rewriting (rank 0 keeps the plain path)
        path = rank_suffix_path(_cur["path"])
        try:
            w = open(path, "a", buffering=1)
        except OSError as e:
            warn_rate_limited(
                _logger(), "metrics-timeline:open", 60,
                "cannot open MXNET_TPU_METRICS file %s (%s) — timeline "
                "export disabled for this run", path, e)
            _cur["path"] = None
            return
        _cur["writer"] = w
        _cur["abs_path"] = os.path.abspath(path)
    # one write() of a complete line (line-buffered flush): a tailing
    # reader sees whole records or nothing
    try:
        w.write(json.dumps(sample, separators=(",", ":"),
                           default=repr) + "\n")
    except (OSError, ValueError) as e:
        # same contract as the open failure: say why the export went
        # dark (disk full, bad fd) and stop paying for dead writes —
        # the in-memory ring keeps recording either way
        warn_rate_limited(
            _logger(), "metrics-timeline:write", 60,
            "writing MXNET_TPU_METRICS sample to %s failed (%s) — "
            "timeline export disabled for this run, ring still "
            "recording", _cur["abs_path"], e)
        _cur["path"] = None
        _close_writer()
        return
    _agg["written"] += 1


# ------------------------------------------------------------ read side


def samples():
    """The in-memory ring, oldest first (host dicts; safe to mutate)."""
    return [dict(s) for s in _ring]


# samples embedded per diag dump: plenty for every trend window (the
# rules compare series quarters) while keeping the dump — and the
# MXNET_TPU_DIAG_PUSH payload serialized on the training thread —
# bounded well below the full ring
EMBED_TAIL = 256


def timeline(tail=EMBED_TAIL):
    """The ring's newest ``tail`` samples as an embeddable dump
    section: ``{"interval", "samples": [...]}``, or None while empty —
    what ``runtime_stats.diag_snapshot`` attaches so a diag dump
    carries the recent time series for the trend doctor."""
    if not _ring:
        return None
    out = samples()
    if tail is not None:
        out = out[-tail:]
    return {"interval": _cur["interval"], "samples": out}


def looks_like_sample(data):
    """True for a dict shaped like one timeline sample — what a
    one-line JSONL file parses to (it IS valid JSON, so plain
    ``json.loads`` sniffing would misread it as a diag dump)."""
    return isinstance(data, dict) and "wall_ms" in data \
        and "snapshot" not in data and "ops" not in data \
        and "traceEvents" not in data


def snapshot():
    """Small JSON-ready status view (never the full ring)."""
    last = _ring[-1] if _ring else None
    return {"enabled": _state["on"], "step": _cur["step"],
            "interval": _cur["interval"], "samples": len(_ring),
            "written": _agg["written"], "path": _cur["abs_path"]
            or _cur["path"], "port": server_port(),
            "last": dict(last) if last else None}


def parse_jsonl(text):
    """Parse JSONL text into a sample list.  Blank lines are skipped; a
    trailing torn line (a crash mid-append) is dropped, not fatal; and
    only dict lines count — scalar-per-line garbage must not pass as a
    valid (rule-silent) timeline."""
    out = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def sniff_text(text, path="<input>"):
    """THE content sniffer every timeline-aware loader shares
    (``perfdoctor.classify``, ``runtime_stats.load_dumps``,
    :func:`load`): returns ``("timeline", {"samples": [...]})``,
    ``("trace", data)``, or ``("dump", data)``.  Content that is
    neither JSON nor sample-bearing JSONL raises ``ValueError`` — a
    corrupt input must never read as a finding-free clean run."""
    try:
        data = json.loads(text)
    except ValueError:
        samples = parse_jsonl(text)
        if not samples:
            raise ValueError(
                "%s is neither JSON nor a metrics JSONL timeline"
                % path) from None
        return "timeline", {"samples": samples}
    if isinstance(data, list):
        samples = [s for s in data if isinstance(s, dict)]
        if not samples:
            raise ValueError(
                "%s is a JSON array with no timeline samples" % path)
        return "timeline", {"samples": samples}
    if looks_like_sample(data):
        return "timeline", {"samples": [data]}
    if isinstance(data, dict) and "traceEvents" in data:
        return "trace", data
    if not isinstance(data, dict):
        raise ValueError(
            "%s is neither a diag dump, chrome trace, nor metrics "
            "timeline" % path)
    return "dump", data


def load(path):
    """Samples from a timeline source: a JSONL file (even a one-line
    one), a JSON sample array, or a diag dump embedding a ``timeline``
    section (a dump without one yields ``[]``).  Non-JSON/JSONL
    content raises ``ValueError`` (:func:`sniff_text`)."""
    with open(path) as f:
        text = f.read()
    kind, data = sniff_text(text, path=path)
    if kind == "timeline":
        return data["samples"]
    tl = data.get("timeline")
    if isinstance(tl, dict):
        return tl.get("samples") or []
    return tl or []


def _fmt(v, fmt="%.2f"):
    return "-" if v is None else fmt % v


def render(samp, tail=30):
    """Text table of a sample list (the CLI / ``diagnose.py --timeline``
    view): newest ``tail`` rows plus a summary line."""
    lines = ["Live metrics timeline (%d sample(s)%s)"
             % (len(samp),
                ", steps %s-%s" % (samp[0].get("step", "?"),
                                   samp[-1].get("step", "?"))
                if samp else "")]
    if not samp:
        lines.append("(no samples — MXNET_TPU_METRICS=<file> / "
                     "MXNET_TPU_METRICS_PORT=<port>, or auto-on under "
                     "MXNET_TPU_PROFILE / MXNET_TPU_DIAG)")
        return "\n".join(lines)
    lines.append("%8s %9s %9s %9s %9s %8s %10s %5s"
                 % ("Step", "Wall ms", "Thr/s", "Live MB", "Peak MB",
                    "Compiles", "kv p99 ms", "NaN"))
    for s in samp[-tail:]:
        kv = s.get("kv_rtt_ms") or {}
        push = kv.get("kv:push_rtt") or {}
        lines.append("%8s %9s %9s %9s %9s %8d %10s %5s"
                     % (s.get("step", "?"), _fmt(s.get("wall_ms"), "%.3f"),
                        _fmt(s.get("throughput"), "%.1f"),
                        _fmt((s.get("live_bytes") or 0) / 1e6),
                        _fmt((s.get("peak_bytes") or 0) / 1e6),
                        s.get("compiles", 0),
                        _fmt(push.get("p99_ms"), "%.3f"),
                        "*" if s.get("nan") or s.get("inf") else ""))
    walls = [s["wall_ms"] for s in samp if s.get("wall_ms") is not None]
    thrs = [s["throughput"] for s in samp if s.get("throughput")]
    lives = [s.get("live_bytes") for s in samp
             if s.get("live_bytes") is not None]
    parts = []
    if walls:
        parts.append("mean wall %.3f ms" % (sum(walls) / len(walls)))
    if thrs:
        parts.append("mean throughput %.1f/s" % (sum(thrs) / len(thrs)))
    if lives:
        parts.append("live bytes %s -> %s MB"
                     % (_fmt(lives[0] / 1e6), _fmt(lives[-1] / 1e6)))
    if parts:
        lines.append("summary: " + "; ".join(parts))
    lines.append("(trend analysis: python tools/diagnose.py --doctor "
                 "<this file or its diag dump>)")
    return "\n".join(lines)


# -------------------------------------------------- Prometheus endpoint


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name):
    n = _NAME_RE.sub("_", name)
    if not n or not (n[0].isalpha() or n[0] == "_"):
        n = "_" + n
    return n


def _prom_label(value):
    return str(value).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _prom_num(v):
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return "%.10g" % v


def prometheus_text():
    """The ``/metrics`` payload: Prometheus text format (version 0.0.4)
    built from snapshot reads only — counters and per-op totals from
    ``runtime_stats``, device-memory / jit-cache / health-queue gauges,
    the newest timeline sample's step gauges, and every latency
    histogram (plus the stepstats phases) as a ``summary`` family."""
    from . import health as _health
    from . import runtime_stats as _rts

    lines = []

    def family(name, mtype, help_, rows):
        # rows: [(labels-dict-or-None, value)]; suffix rides in name
        emitted = False
        for labels, v in rows:
            if v is None:
                continue
            if not emitted:
                lines.append("# HELP %s %s" % (name, help_))
                lines.append("# TYPE %s %s" % (name, mtype))
                emitted = True
            lab = ""
            if labels:
                lab = "{%s}" % ",".join(
                    '%s="%s"' % (k, _prom_label(v2))
                    for k, v2 in labels.items())
            lines.append("%s%s %s" % (name, lab, _prom_num(float(v))))

    ident = process_identity()
    family("mxnet_tpu_identity", "gauge",
           "Process identity under the DMLC_* launch contract.",
           [({"role": ident["role"], "rank": ident["rank"]}, 1)]
           if ident else [(None, 1)])

    # dispatch totals (counter semantics: monotonic for process life)
    totals = {"op_calls": 0, "jit_cache_hits": 0, "jit_cache_misses": 0,
              "fallbacks": 0, "compile_seconds": 0.0,
              "dispatch_seconds": 0.0}
    for s in list(_rts._PER_OP.values()):
        totals["op_calls"] += s["calls"]
        totals["jit_cache_hits"] += s["hits"]
        totals["jit_cache_misses"] += s["misses"]
        totals["fallbacks"] += s["fallbacks"]
        totals["compile_seconds"] += s["compile_seconds"]
        totals["dispatch_seconds"] += s.get("dispatch_seconds", 0.0)
    for key, help_ in (("op_calls", "Op dispatches."),
                       ("jit_cache_hits", "Jit-cache hits."),
                       ("jit_cache_misses", "Jit-cache misses."),
                       ("fallbacks", "Dispatches off the compiled path."),
                       ("compile_seconds", "Compile wall seconds."),
                       ("dispatch_seconds",
                        "Cache-warm dispatch wall seconds.")):
        family("mxnet_tpu_%s_total" % key, "counter", help_,
               [(None, totals[key])])
    # generic named counters (trainer_steps, kvstore_retries, ...)
    for name, v in sorted(list(_rts._COUNTERS.items())):
        family("mxnet_tpu_%s_total" % _prom_name(name), "counter",
               "runtime_stats counter %r." % name, [(None, v)])

    live, peak = _dm.live_totals()
    family("mxnet_tpu_device_live_bytes", "gauge",
           "Live tracked device bytes.", [(None, live)])
    family("mxnet_tpu_device_peak_bytes", "gauge",
           "Peak tracked device bytes.", [(None, peak)])
    family("mxnet_tpu_jit_cache_entries", "gauge",
           "Jit-cache entries across the op registry.",
           [(None, _jit_cache_size())])
    if _health._state["on"] and _health._GLOBAL:
        family("mxnet_tpu_health_pending", "gauge",
               "Queued (undrained) health stat entries.",
               [(None, len(_health._GLOBAL[0]._pending))])
    family("mxnet_tpu_timeline_samples", "gauge",
           "Samples in the metrics-timeline ring.", [(None, len(_ring))])

    last = _ring[-1] if _ring else None
    if last:
        family("mxnet_tpu_step", "gauge",
               "Step number of the newest timeline sample.",
               [(None, last.get("step"))])
        wall = last.get("wall_ms")
        family("mxnet_tpu_step_duration_seconds", "gauge",
               "Newest sampled step wall time.",
               [(None, wall / 1e3 if wall is not None else None)])
        family("mxnet_tpu_step_throughput_samples_per_second", "gauge",
               "Newest sampled training throughput.",
               [(None, last.get("throughput"))])
        phases = last.get("phases_ms") or {}
        family("mxnet_tpu_step_phase_seconds", "gauge",
               "Newest step's per-phase wall time (stepstats).",
               [({"phase": p}, v / 1e3)
                for p, v in sorted(phases.items())])

    # fused-step x-ray: newest per-scope cost shares per program label
    # (xray.py tables — snapshot reads only; a process that never
    # compiled a whole-step program pays a sys.modules lookup)
    import sys as _sys

    _cs = _sys.modules.get("mxnet_tpu.compiled_step")
    xprogs = (_cs.xray_snapshot() if _cs is not None
              else {}).get("programs") or []
    if xprogs:
        newest = {}
        for t in xprogs:  # seq-sorted: later wins
            newest[t.get("label", "compiled_step")] = t
        xrows = []
        for label, t in sorted(newest.items()):
            srows = dict(t.get("scopes") or {})
            srows["unattributed"] = t.get("unattributed") or {}
            for scope, rec in sorted(srows.items()):
                for metric in ("flops", "bytes"):
                    xrows.append((
                        {"program": label, "scope": scope,
                         "metric": metric},
                        rec.get("%s_share" % metric)))
        family("mxnet_tpu_xray_scope_share", "gauge",
               "Newest compiled whole-step program's per-scope share "
               "of whole-program flops/bytes (fused-step x-ray; "
               "unattributed remainder completes the sum to 1).",
               xrows)

    # live perfdoctor findings as a gauge family: external alerting
    # reads the SAME signal the autopilot's reflexes act on.  Snapshot
    # reads only, and a diagnosis failure must never fail the scrape.
    try:
        from . import perfdoctor as _doctor

        findings = _doctor.live_findings()
    except Exception:
        findings = []
    if findings:
        # one series per (rule, severity): several findings of one rule
        # (e.g. per-shard kv drift) collapse to the max score — a
        # Prometheus family must not repeat a label-set
        by_labels = {}
        for f in findings:
            key = (f["rule"], f["severity"])
            if f["score"] > by_labels.get(key, (None, -1.0))[1]:
                by_labels[key] = (f, f["score"])
        family("mxnet_tpu_doctor_finding", "gauge",
               "Live perfdoctor findings (score = estimated share of "
               "step time at stake); absent series = rule quiet.",
               [({"rule": rule, "severity": sev}, score)
                for (rule, sev), (_f, score) in sorted(
                    by_labels.items())])

    # SLO objectives: budget remaining + per-window burn rates — an
    # external alerter pages on the SAME multi-window verdicts the
    # doctor rules and the slo-shed reflex read.  Snapshot reads only;
    # an evaluation failure must never fail the scrape.
    try:
        from . import slo as _slo

        slo_objs = _slo.snapshot().get("objectives") or []
    except Exception:
        slo_objs = []
    if slo_objs:
        family("mxnet_tpu_slo_target", "gauge",
               "Declared SLO target (fraction of good events).",
               [({"objective": ob["name"]}, ob["target"])
                for ob in slo_objs])
        family("mxnet_tpu_slo_budget_remaining", "gauge",
               "Error budget remaining (1 = untouched, <= 0 = "
               "exhausted; overall bad-rate over budget).",
               [({"objective": ob["name"]}, ob["budget_remaining"])
                for ob in slo_objs])
        family("mxnet_tpu_slo_bad_total", "counter",
               "Requests counted against the objective.",
               [({"objective": ob["name"]}, ob["bad"])
                for ob in slo_objs])
        family("mxnet_tpu_slo_good_total", "counter",
               "Requests inside the objective.",
               [({"objective": ob["name"]}, ob["good"])
                for ob in slo_objs])
        family("mxnet_tpu_slo_burn_rate", "gauge",
               "Window error rate over budget (burn 1.0 = spending "
               "exactly the budget; fast pair 5m/1h pages at >= 14.4, "
               "slow pair 30m/6h at >= 6.0).",
               [({"objective": ob["name"], "window": label},
                 (ob["windows"].get(label) or {}).get("burn"))
                for ob in slo_objs
                for label, _span in _slo.WINDOWS])

    # every latency histogram as one summary family (associative
    # snapshots — the same numbers report()/cluster_report show).
    # serve:* p99 rows carry an OpenMetrics-style exemplar naming the
    # slowest request the x-ray ring retained, so a dashboard can jump
    # from the quantile straight to one traced request id.
    try:
        from . import reqtrace as _reqtrace

        _exemplar = _reqtrace.exemplar()
    except Exception:
        _exemplar = None
    rows = []
    for name, h in sorted(list(_histogram._HISTS.items())):
        snap = h.snapshot()
        if not snap["count"]:
            continue
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            rows.append((name, key,
                         {"series": name, "quantile": "%g" % q},
                         snap[key]))
    if rows:
        lines.append("# HELP mxnet_tpu_latency_seconds Latency "
                     "distributions (histogram.py log2 buckets).")
        lines.append("# TYPE mxnet_tpu_latency_seconds summary")
        for name, key, labels, v in rows:
            suffix = ""
            if (_exemplar is not None and key == "p99"
                    and name.startswith("serve")):
                suffix = ' # {request_id="%s"} %s' % (
                    _exemplar[0], _prom_num(_exemplar[1]))
            lines.append("mxnet_tpu_latency_seconds{%s} %s%s" % (
                ",".join('%s="%s"' % (k, _prom_label(v2))
                         for k, v2 in labels.items()), _prom_num(v),
                suffix))
        for name, h in sorted(list(_histogram._HISTS.items())):
            if not h.count:
                continue
            lines.append('mxnet_tpu_latency_seconds_sum{series="%s"} %s'
                         % (_prom_label(name), _prom_num(h.total)))
            lines.append('mxnet_tpu_latency_seconds_count{series="%s"} %s'
                         % (_prom_label(name), _prom_num(h.count)))
    return "\n".join(lines) + "\n"


def serve(port=None, host=None):
    """Start (or restart) the read-only ``/metrics`` HTTP endpoint on a
    daemon thread; returns the server (its bound port is
    ``server_port()``).  Serves snapshots only: no health drain, no
    device access, no writes.

    Binds every interface by default (the node-exporter convention — a
    Prometheus scraper is usually remote); the payload is read-only
    runtime telemetry, but on an untrusted network set
    ``MXNET_TPU_METRICS_HOST=127.0.0.1`` (or ``host=``) to keep it
    loopback-only."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    stop_server()
    if host is None:
        host = os.environ.get("MXNET_TPU_METRICS_HOST", "")
    if port is None:
        try:
            port = int(os.environ.get("MXNET_TPU_METRICS_PORT", "0"))
        except ValueError:
            port = 0

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                self.send_error(404, "only /metrics is served")
                return
            try:
                body = prometheus_text().encode("utf-8")
            except Exception:  # pragma: no cover - a scrape must not 500
                _logger().exception("metrics render failed")
                self.send_error(500)
                return
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="mxtpu-metrics", daemon=True)
    t.start()
    _server.append(srv)
    return srv


def server_port():
    """The endpoint's bound port, or None when not serving."""
    return _server[0].server_address[1] if _server else None


def stop_server():
    """Shut the endpoint down (idempotent)."""
    while _server:
        srv = _server.pop()
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:
            pass


# ------------------------------------------------------- env activation


def _activate_from_env():
    """Import-time arming — called by ``runtime_stats`` once its module
    globals exist (``enable()`` raises stepstats/histogram state there).
    ``MXNET_TPU_METRICS``/``MXNET_TPU_METRICS_PORT`` arm their exports;
    ``MXNET_TPU_PROFILE``/``MXNET_TPU_DIAG`` arm the ring alone."""
    path = os.environ.get("MXNET_TPU_METRICS")
    port_raw = os.environ.get("MXNET_TPU_METRICS_PORT")
    port = None
    want_port = bool(port_raw)
    if port_raw:
        try:
            port = int(port_raw)
        except ValueError:
            # the user explicitly asked for the endpoint: a typo'd
            # port must not silently drop the whole timeline
            warn_rate_limited(
                _logger(), "metrics-timeline:port", 60,
                "MXNET_TPU_METRICS_PORT=%r is not a port number — "
                "/metrics endpoint disabled, timeline ring still "
                "recording", port_raw)
    if not (path or want_port
            or os.environ.get("MXNET_TPU_PROFILE")
            or os.environ.get("MXNET_TPU_DIAG")):
        return False
    try:
        enable(path=path, port=port)
    except OSError as e:
        # a busy metrics port must never kill training: keep the ring
        warn_rate_limited(
            _logger(), "metrics-timeline:port", 60,
            "cannot bind MXNET_TPU_METRICS_PORT=%s (%s) — /metrics "
            "endpoint disabled, timeline ring still recording",
            port_raw, e)
        if not _state["on"]:
            enable(path=path, port=None)
    return True
