"""Colored logging helpers (reference: python/mxnet/log.py).

``get_logger`` attaches a glog-style formatter: one colored severity
letter + timestamp + pid + source location, then the message.
"""

from __future__ import annotations

import logging
import os
import sys
import time

__all__ = ["get_logger", "getLogger", "warn_rate_limited", "warn_once",
           "reset_rate_limits", "process_identity", "rank_suffix_path",
           "CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG", "NOTSET"]

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

_COLORS = ((logging.WARNING, "\x1b[31m"), (logging.INFO, "\x1b[32m"),
           (logging.NOTSET, "\x1b[34m"))
_LABELS = {logging.CRITICAL: "C", logging.ERROR: "E", logging.WARNING: "W",
           logging.INFO: "I", logging.DEBUG: "D"}


class _GlogFormatter(logging.Formatter):
    def __init__(self):
        super().__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        color = next(c for lvl, c in _COLORS if record.levelno >= lvl)
        label = _LABELS.get(record.levelno, "U")
        self._style._fmt = (
            color + label +
            "%(asctime)s %(process)d %(pathname)s:%(funcName)s:%(lineno)d"
            "]\x1b[0m %(message)s")
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """A logger with the glog-style formatter attached once
    (reference: log.py get_logger)."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_log_init", False):
        logger.setLevel(level)
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_GlogFormatter())
    logger.addHandler(handler)
    logger.setLevel(level)
    if name is not None:  # don't double-print through the root handler
        logger.propagate = False
    logger._mxtpu_log_init = True
    return logger


def process_identity():
    """This process's rank/role under the ``DMLC_*``/``MXTPU_*`` launch
    contract (``tools/launch.py``), or None when running single-process.

    ``{"role": "worker"|"server", "rank": int, "num_workers": int}`` —
    the shared identity the distributed-telemetry layer stamps on
    rate-limited warnings, diag-dump headers, and chrome-trace pids so
    multi-rank output is attributable (docs/OBSERVABILITY.md
    "Distributed telemetry").  Read fresh from the env each call: the
    launcher sets these before exec, and tests monkeypatch them."""
    def _int(v, default):
        # a malformed value (unexpanded '$RANK', stray wrapper export)
        # must never crash `import mxnet_tpu` or a warning call
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    env = os.environ
    role = env.get("DMLC_ROLE")
    nw = _int(env.get("DMLC_NUM_WORKER"), 1)
    if role == "server":
        rank = env.get("MXTPU_PS_SERVER_ID", env.get("DMLC_SERVER_ID"))
        return {"role": "server", "rank": _int(rank, 0),
                "num_workers": nw}
    wid = env.get("DMLC_WORKER_ID", env.get("JAX_PROCESS_ID"))
    if role is None and wid is None:
        return None
    return {"role": role or "worker", "rank": _int(wid, 0),
            "num_workers": nw}


def rank_suffix_path(path):
    """Self-suffix an observability output path (trace / diag / metrics
    JSONL / flight dump) with this process's role+rank when running
    multi-process WITHOUT ``tools/launch.py``'s env rewriting.

    Rank-0 workers and single-process runs keep the plain path (the
    single-writer default); every other rank — and servers, whose rank
    space is separate from the workers' — gets
    ``<base>.<role><rank><ext>`` (launch.py's convention) so it can
    never silently clobber rank 0's file.  Paths launch.py already
    suffixed pass through unchanged."""
    if not path:
        return path
    ident = process_identity()
    if ident is None:
        return path
    role, rank = ident["role"], ident["rank"]
    if role != "server" and rank == 0:
        return path
    token = ".%s%d" % (role, rank)
    base, ext = os.path.splitext(path)
    # idempotent against launch.py's rewriting: on an extension-less
    # value the launcher's token lands in the ext slot, not the base
    if base.endswith(token) or ext == token:
        return path
    return base + token + ext


# key -> monotonic time of the last emitted warning
# mxlint: disable=thread-shared-state -- best-effort rate-limit bookkeeping: a race costs at most one duplicate or dropped warning
_rate_state: dict = {}


def warn_rate_limited(logger, key, interval, msg, *args):
    """``logger.warning(msg, *args)`` at most once per ``interval``
    seconds per ``key``; returns True when the warning was emitted.

    Telemetry paths (runtime_stats recompile-storm detector) warn from
    hot loops — without rate limiting a storm of recompiles would also
    be a storm of log lines.  Under a distributed launch the message is
    prefixed with this process's rank/role, so interleaved multi-rank
    stderr stays attributable."""
    now = time.monotonic()
    last = _rate_state.get(key)
    if last is not None and now - last < interval:
        return False
    _rate_state[key] = now
    ident = process_identity()
    if ident is not None:
        msg = "[%s %d] %s" % (ident["role"], ident["rank"], msg)
    logger.warning(msg, *args)
    return True


def warn_once(logger, key, msg, *args):
    """``logger.warning(msg, *args)`` exactly once per ``key`` for the
    process lifetime (re-armed by :func:`reset_rate_limits`) — for
    events that matter once, like the health layer's crash-path
    flight-recorder dump notice."""
    return warn_rate_limited(logger, key, float("inf"), msg, *args)


def reset_rate_limits(prefix=None):
    """Re-arm rate-limited warnings (all keys, or those under a prefix)."""
    if prefix is None:
        _rate_state.clear()
        return
    for k in [k for k in _rate_state if k.startswith(prefix)]:
        del _rate_state[k]


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias of :func:`get_logger` (reference parity)."""
    import warnings

    warnings.warn("getLogger is deprecated; use get_logger",
                  DeprecationWarning, stacklevel=2)
    return get_logger(name, filename, filemode, level)
