"""mx.operator — Python custom operators.

Reference: python/mxnet/operator.py (CustomOp, CustomOpProp,
register) over src/operator/custom/custom.cc:103 — user-defined ops
callable from both the imperative and symbolic paths.

TPU-native notes: the reference runs Python callbacks on a separate
thread pool to keep the engine async.  Here a custom op is a host
callback: in eager mode it runs directly on NDArrays; inside a staged
graph (hybridize/Symbol executor) it is wrapped in
``jax.pure_callback`` so XLA calls back into Python — the analog of
the reference's dedicated custom-op thread (custom-inl.h:50).
Gradients route through the user's ``backward`` via the tape.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray
from .ndarray import array as _nd_array

__all__ = ["CustomOp", "CustomOpProp", "register", "get_custom_op"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for user ops (reference: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst._assign(src._data if isinstance(src, NDArray)
                        else _nd_array(src)._data)
        elif req == "add":
            dst._assign(dst._data + (src._data if isinstance(src, NDArray)
                                     else _nd_array(src)._data))


class CustomOpProp:
    """Op metadata provider (reference: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp under a name
    (reference: operator.py register → MXCustomOpRegister)."""

    def deco(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        _install_custom(reg_name, prop_cls)
        return prop_cls

    return deco


def get_custom_op(name):
    return _CUSTOM_REGISTRY[name]


def _install_custom(reg_name, prop_cls):
    """Expose the op as mx.nd.Custom(..., op_type=reg_name) and as a
    callable mx.nd.<reg_name>."""
    from . import ndarray as nd_mod
    from .ops import registry as _reg

    def run_custom(*inputs, **kwargs):
        import jax

        kwargs.pop("name", None)
        op_type = kwargs.pop("op_type", reg_name)
        in_nds = [x if isinstance(x, NDArray) else _nd_array(x) for x in inputs]
        if any(isinstance(x._data, jax.core.Tracer) for x in in_nds):
            # staged graph (hybridize / symbolic executor): run through
            # the `Custom` registry op — pure_callback + custom_vjp
            from .ops.registry import apply_op

            res = apply_op("Custom", *[x._data for x in in_nds],
                           op_type=op_type, **kwargs)
            if isinstance(res, (tuple, list)):
                return [NDArray(r) for r in res]
            return NDArray(res)
        prop = _CUSTOM_REGISTRY[op_type](**{k: str(v) for k, v in kwargs.items()})
        in_shapes = [x.shape for x in in_nds]
        _ins, out_shapes, aux_shapes = prop.infer_shape(list(in_shapes))
        op = prop.create_operator(None, in_shapes,
                                  [x.dtype for x in in_nds])
        from . import autograd as _ag

        outs = [nd_mod.zeros(s) for s in out_shapes]
        aux = [nd_mod.zeros(s) for s in aux_shapes]
        with _ag.pause():
            op.forward(_ag.is_training(), ["write"] * len(outs), in_nds, outs,
                       aux)

        if _ag.is_recording() and _ag._any_recorded(in_nds):
            def vjp_fn(cts):
                cts = cts if isinstance(cts, tuple) else (cts,)
                out_grads = [NDArray(c) for c in cts]
                in_grads = [nd_mod.zeros(s) for s in in_shapes]
                with _ag.pause():
                    op.backward(["write"] * len(in_grads), out_grads, in_nds,
                                outs, in_grads, aux)
                return tuple(g._data for g in in_grads)

            _ag.record_op(in_nds, outs, vjp_fn)
        return outs if len(outs) > 1 else outs[0]

    setattr(nd_mod, reg_name, run_custom)
    # Custom(op_type=...) entry point
    if not hasattr(nd_mod, "Custom"):
        def Custom(*inputs, **kwargs):
            op_type = kwargs.get("op_type")
            if op_type is None:
                raise MXNetError("Custom requires op_type=")
            fn = getattr(nd_mod, op_type, None)
            if fn is None:
                raise MXNetError("custom op %r not registered" % op_type)
            return fn(*inputs, **kwargs)

        nd_mod.Custom = Custom
