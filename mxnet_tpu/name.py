"""Name-scope management (reference: python/mxnet/name.py).

The implementation lives in ``mxnet_tpu.base``; this module keeps the
reference import path ``from mxnet.name import NameManager, Prefix``.
"""

from .base import NameManager  # noqa: F401


class Prefix(NameManager):
    """Prepends a fixed prefix to every auto-generated name
    (reference: name.py Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        # the reference prefixes EXPLICIT names too
        return self._prefix + super().get(name, hint)
