"""PRNG management — TPU-native replacement for the reference RNG resource.

Reference: include/mxnet/random_generator.h, src/resource.cc (kRandom /
kParallelRandom resources), python/mxnet/random.py (mx.random.seed).

Design: a process-global counter-based key chain (jax threefry).  Eager
ops call ``next_key()`` for a fresh key.  Inside a CachedOp/Executor
trace, a :class:`TraceRNG` scope is active instead: keys derive from a
*traced* seed input by ``fold_in`` of a per-trace counter, so compiled
graphs get fresh randomness every call without retracing — the analog of
the reference passing the RNG resource into kernels at run time rather
than build time.
"""

from __future__ import annotations

import threading

import numpy as _np

__all__ = ["seed", "next_key", "TraceRNG", "get_state", "set_state"]

_state = threading.local()


def _global():
    if not hasattr(_state, "rng"):
        _state.rng = {"seed": _np.random.randint(0, 2**31 - 1), "counter": 0}
    return _state.rng


def seed(seed_state, ctx="all"):
    """Seed the framework RNG (reference: python/mxnet/random.py:seed).

    Also seeds numpy-side shuffling used by data iterators.
    """
    g = _global()
    g["seed"] = int(seed_state)
    g["counter"] = 0


class TraceRNG:
    """Scope active while tracing a graph: keys derive from a traced seed."""

    _active = threading.local()

    def __init__(self, key_tracer):
        self.key = key_tracer
        self.counter = 0

    def __enter__(self):
        stack = getattr(TraceRNG._active, "stack", None)
        if stack is None:
            stack = TraceRNG._active.stack = []
        stack.append(self)
        return self

    def __exit__(self, *a):
        TraceRNG._active.stack.pop()

    @classmethod
    def current(cls):
        stack = getattr(cls._active, "stack", None)
        return stack[-1] if stack else None


def next_key():
    """A fresh PRNG key (eager) or traced derived key (inside a trace)."""
    import jax

    tr = TraceRNG.current()
    if tr is not None:
        tr.counter += 1
        return jax.random.fold_in(tr.key, tr.counter)
    g = _global()
    g["counter"] += 1
    return jax.random.fold_in(jax.random.PRNGKey(g["seed"]), g["counter"])


def get_state():
    return dict(_global())


def set_state(state):
    """Restore a :func:`get_state` snapshot — seed AND key counter — so
    a checkpoint-resumed run continues the exact key chain an
    uninterrupted run would have used (``mxnet_tpu.checkpoint`` stores
    this in every manifest)."""
    g = _global()
    g["seed"] = int(state["seed"])
    g["counter"] = int(state.get("counter", 0))
