"""ctypes bindings for the native host runtime (libmxtpu.so).

The reference loads libmxnet once and wraps its C ABI with ctypes
(python/mxnet/base.py:578 _LIB); same pattern here.  The library is built
on demand from mxnet_tpu/native/ with `make` (g++, no external deps) and
cached; every entry point degrades to a pure-Python fallback when the
toolchain is unavailable, so the framework never hard-requires the .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmxtpu.so")

_lib = None
_lib_err = None
_lock = threading.Lock()

# Decode callback: (ctx, rec_ptr, rec_len, data_out, label_out) -> int
DECODE_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_float))

# Engine op callback: (ctx, op_id) -> int
ENGINE_OP_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                ctypes.c_uint64)


def _build():
    env = dict(os.environ)
    subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True, env=env,
                   capture_output=True)


def _stale():
    """True when the .so is missing or older than any native source."""
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    srcs = [os.path.join(_NATIVE_DIR, "Makefile")]
    src_dir = os.path.join(_NATIVE_DIR, "src")
    for f in os.listdir(src_dir):
        srcs.append(os.path.join(src_dir, f))
    return any(os.path.getmtime(s) > so_mtime for s in srcs
               if os.path.exists(s))


def _declare(lib):
    u64 = ctypes.c_uint64
    vp = ctypes.c_void_p
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    lib.MXTPUGetLastError.argtypes = []
    sigs = {
        "MXTPUEngineCreate": [ctypes.c_int, ctypes.c_int, ctypes.POINTER(vp)],
        "MXTPUEngineFree": [vp],
        "MXTPUEngineNewVar": [vp, ctypes.POINTER(u64)],
        "MXTPUEngineDelVar": [vp, u64],
        "MXTPUEnginePush": [vp, ENGINE_OP_FN, vp, ctypes.POINTER(u64),
                            ctypes.c_int, ctypes.POINTER(u64), ctypes.c_int,
                            ctypes.c_int, ctypes.c_char_p,
                            ctypes.POINTER(u64)],
        "MXTPUEngineOnComplete": [vp, u64],
        "MXTPUEngineOnCompleteError": [vp, u64, ctypes.c_char_p],
        "MXTPUEngineWaitForVar": [vp, u64],
        "MXTPUEngineWaitAll": [vp],
        "MXTPUEngineNumPending": [vp, ctypes.POINTER(ctypes.c_int64)],
        "MXTPURecordReaderCreate": [ctypes.c_char_p, u64, ctypes.c_int,
                                    ctypes.c_int, ctypes.POINTER(vp)],
        "MXTPURecordReaderNext": [vp, ctypes.POINTER(
            ctypes.POINTER(ctypes.c_uint8)), ctypes.POINTER(ctypes.c_uint32)],
        "MXTPURecordReaderReset": [vp],
        "MXTPURecordReaderFree": [vp],
        "MXTPURecordWriterCreate": [ctypes.c_char_p, ctypes.POINTER(vp)],
        "MXTPURecordWriterWrite": [vp, ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_uint32, ctypes.POINTER(u64)],
        "MXTPURecordWriterFree": [vp],
        "MXTPUPipelineCreate": [ctypes.c_char_p, u64, ctypes.c_int,
                                ctypes.c_int, ctypes.c_int, u64, ctypes.c_int,
                                ctypes.c_int, u64, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, DECODE_FN, vp,
                                ctypes.POINTER(vp)],
        "MXTPUPipelineCreateJpeg": [ctypes.c_char_p, u64, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int, u64,
                                    ctypes.c_int, ctypes.c_int, u64,
                                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_float, ctypes.c_float,
                                    ctypes.c_float, DECODE_FN, vp,
                                    ctypes.POINTER(vp)],
        "MXTPUPipelineHasJpeg": [],
        "MXTPUPipelineNext": [vp, ctypes.POINTER(
            ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int)],
        "MXTPUPipelineRelease": [vp, ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.POINTER(ctypes.c_float)],
        "MXTPUPipelineReset": [vp],
        "MXTPUPipelineFree": [vp],
        # predict ABI (reference: c_predict_api.h MXPred*)
        "MXTPUPredCreate": [ctypes.c_char_p, vp, u64, ctypes.c_int,
                            ctypes.c_int, ctypes.c_uint32,
                            ctypes.POINTER(ctypes.c_char_p),
                            ctypes.POINTER(ctypes.c_uint32),
                            ctypes.POINTER(ctypes.c_uint32),
                            ctypes.POINTER(vp)],
        "MXTPUPredSetInput": [vp, ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_float), u64],
        "MXTPUPredForward": [vp],
        "MXTPUPredGetOutputShape": [vp, ctypes.c_uint32,
                                    ctypes.POINTER(
                                        ctypes.POINTER(ctypes.c_uint32)),
                                    ctypes.POINTER(ctypes.c_uint32)],
        "MXTPUPredGetOutput": [vp, ctypes.c_uint32,
                               ctypes.POINTER(ctypes.c_float), u64],
        "MXTPUPredReshape": [ctypes.c_uint32,
                             ctypes.POINTER(ctypes.c_char_p),
                             ctypes.POINTER(ctypes.c_uint32),
                             ctypes.POINTER(ctypes.c_uint32), vp,
                             ctypes.POINTER(vp)],
        "MXTPUPredFree": [vp],
    }
    for name, argtypes in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = ctypes.c_int


def get_lib():
    """Load (building if needed) libmxtpu; returns None when unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            # rebuild only when sources changed; a failed rebuild over an
            # existing (but stale) .so must NOT fall through to loading
            # it — _declare would reject missing symbols and silently
            # disable the whole native runtime
            if _stale():
                _build()
            lib = ctypes.CDLL(_SO_PATH)
            _declare(lib)
            _lib = lib
        except Exception as e:  # toolchain missing, etc.
            _lib_err = e
    return _lib


def available():
    return get_lib() is not None


def check_call(ret):
    if ret != 0:
        raise RuntimeError(
            get_lib().MXTPUGetLastError().decode("utf-8", "replace"))
