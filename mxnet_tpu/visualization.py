"""Network visualization (reference: python/mxnet/visualization.py):
print_summary (layer table with params/shapes) and plot_network
(graphviz dot source; rendering optional).
"""

from __future__ import annotations

import json

import numpy as _np

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64,
                                                                  0.74, 1.0)):
    """reference: visualization.print_summary."""
    if shape is None:
        raise MXNetError("Input shape is required to print the summary")
    show_shape = True
    _, out_shapes, _ = symbol.infer_shape(**shape)
    arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
    arg_names = symbol.list_arguments()
    arg_shape_dict = dict(zip(arg_names, arg_shapes))

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {h[0] for h in conf["heads"]}
    positions = [int(line_length * p) for p in positions]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"],
              positions)
    print("=" * line_length)
    total_params = 0
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        pre_nodes = [nodes[item[0]]["name"] for item in node["inputs"]
                     if nodes[item[0]]["op"] != "null"]
        cur_param = 0
        for item in node["inputs"]:
            input_name = nodes[item[0]]["name"]
            if nodes[item[0]]["op"] == "null" and input_name in arg_shape_dict:
                if input_name.startswith(name):
                    cur_param += int(_np.prod(arg_shape_dict[input_name]))
        first_connection = pre_nodes[0] if pre_nodes else ""
        fields = ["%s(%s)" % (name, op), "", cur_param, first_connection]
        print_row(fields, positions)
        for conn in pre_nodes[1:]:
            print_row(["", "", "", conn], positions)
        total_params += cur_param
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz dot source for the graph (reference: plot_network).

    Returns a source-holding object with ``.source`` and ``.render``;
    uses the graphviz package if installed, else a minimal stand-in.
    """
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    lines = ["digraph %s {" % json.dumps(title), "  rankdir=BT;"]
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and not (name.endswith("data") or
                                     name.endswith("label")):
                continue
            lines.append('  n%d [label="%s", shape=oval];' % (i, name))
        else:
            label = "%s\\n%s" % (op, name)
            lines.append('  n%d [label="%s", shape=box];' % (i, label))
    visible = {i for i, n in enumerate(nodes)
               if n["op"] != "null" or not hide_weights
               or n["name"].endswith("data") or n["name"].endswith("label")}
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            j = item[0]
            if j in visible:
                lines.append("  n%d -> n%d;" % (j, i))
    lines.append("}")
    source = "\n".join(lines)
    try:
        import graphviz

        dot = graphviz.Source(source)
        return dot
    except ImportError:
        class _Dot:
            def __init__(self, src):
                self.source = src

            def render(self, filename=None, **kwargs):
                fname = (filename or title) + ".dot"
                with open(fname, "w") as f:
                    f.write(self.source)
                return fname

            def _repr_svg_(self):
                return None

        return _Dot(source)
