"""Detection-aware augmentation + ImageDetIter (the SSD data path).

Reference surface: python/mxnet/image/detection.py — DetAugmenter (:39),
CreateDetAugmenter (:482), ImageDetIter (:624) — and the native
ImageDetRecordIter (src/io/iter_image_det_recordio.cc:597 with
image_det_aug_default.cc).

Label wire format (pinned by tests/test_image_detection.py): a packed
record label is a flat float vector
    [header_width, obj_width, <extra header...>, obj0..., obj1..., ...]
where header_width >= 2, obj_width >= 5 and every object row is
[cls, xmin, ymin, xmax, ymax, ...] with corners normalized to [0, 1].
Batched labels are padded with -1 rows up to the epoch-wide max object
count, which is what MultiBoxTarget consumes (cls < 0 rows are ignored).

TPU-native notes: the label-aware geometry is vectorized host numpy and
runs inside the iterator/prefetch threads — the same host/device split
as the reference's OpenCV OMP workers; the batch crosses to HBM once.
There is no separate C++ det iterator: the native chunked record reader
(native/src/recordio.cc) is label-layout agnostic, and the det-specific
work (bbox transforms, -1 padding) is pure numpy on the decoded sample,
so this module is the documented Python equivalent of
iter_image_det_recordio.cc.
"""

from __future__ import annotations

import json
import logging
import random

import numpy as _np

from . import io as _io
from . import ndarray
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, HueJitterAug, ImageIter, LightingAug,
                    RandomGrayAug, ResizeAug, _SampleScopedStream, _like,
                    _to_host, fixed_crop)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


# Python-random twin of image.py's _nprand: det augmenters draw from
# the module-global `random` stream unless a preprocess worker installed
# a per-sample generator (see _SampleScopedStream).
_rand = _SampleScopedStream(random)


# ------------------------------------------------------ box geometry
# Object rows are [cls, x1, y1, x2, y2, ...]; helpers below take the
# (N, 4+) corner slice rows[:, 1:] so column 0..3 = x1, y1, x2, y2.

def _corner_areas(corners):
    """Areas of (N, 4+) normalized corner boxes; degenerate boxes -> 0."""
    w = _np.maximum(0.0, corners[:, 2] - corners[:, 0])
    h = _np.maximum(0.0, corners[:, 3] - corners[:, 1])
    return w * h


def _intersect_window(corners, x1, y1, x2, y2):
    """Clip each corner box to a window; fully-outside boxes -> all-zero."""
    out = corners.copy()
    out[:, 0] = _np.maximum(corners[:, 0], x1)
    out[:, 1] = _np.maximum(corners[:, 1], y1)
    out[:, 2] = _np.minimum(corners[:, 2], x2)
    out[:, 3] = _np.minimum(corners[:, 3], y2)
    dead = (out[:, 0] >= out[:, 2]) | (out[:, 1] >= out[:, 3])
    out[dead] = 0.0
    return out


# ------------------------------------------------------ augmenters


class DetAugmenter:
    """Base label-aware augmenter (reference: detection.py:39).

    __call__(src, label) -> (src, label): src is an HWC image — an
    NDArray, or on the iterator fast path a host array that still
    answers `.asnumpy()` — and label a (N, 5+) numpy array of
    [cls, x1, y1, x2, y2, ...] rows.
    """

    def __init__(self, **kwargs):
        self._kwargs = {
            k: (v.asnumpy().tolist() if isinstance(v, ndarray.NDArray)
                else v.tolist() if isinstance(v, _np.ndarray) else v)
            for k, v in kwargs.items()}

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift a label-invariant classification augmenter into the det
    pipeline (reference: detection.py DetBorrowAug)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("DetBorrowAug wraps classification Augmenters")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def dumps(self):
        return [self.__class__.__name__.lower(), self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Apply one randomly-chosen augmenter from a list, or skip all with
    probability skip_prob (reference: detection.py DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for aug in aug_list:
            if not isinstance(aug, DetAugmenter):
                raise ValueError("DetRandomSelectAug takes DetAugmenters")
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob if aug_list else 1

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [a.dumps() for a in self.aug_list]]

    def __call__(self, src, label):
        if _rand.random() < self.skip_prob:
            return src, label
        return _rand.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p (reference:
    detection.py DetHorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _rand.random() < self.p:
            src = _like(_to_host(src)[:, ::-1].copy(), src)
            label = label.copy()
            x1, x2 = label[:, 1].copy(), label[:, 3].copy()
            label[:, 1] = 1.0 - x2
            label[:, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (reference: detection.py
    DetRandomCropAug): a proposal is accepted when EVERY object it
    overlaps keeps more than min_object_covered of its area (the
    reference's np.amin over positive coverages — overlap-a-sliver
    proposals are rejected rather than silently eating an object), and
    after the crop, objects keeping less than min_eject_coverage of
    their area are dropped from the label.

    Proposal sampling is re-designed: instead of the reference's
    height-first search we sample a target area uniformly in area_range
    and an aspect ratio in aspect_ratio_range, derive (w, h), and
    rejection-sample positions — the accepted crops satisfy the same
    constraint set.
    """

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.min_eject_coverage = min_eject_coverage
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])
        if not self.enabled:
            logging.warning("DetRandomCropAug disabled: invalid ranges %s %s",
                            area_range, aspect_ratio_range)

    def __call__(self, src, label):
        h, w = int(src.shape[0]), int(src.shape[1])
        found = self._propose(label, h, w)
        if found is not None:
            x0, y0, cw, ch, label = found
            src = fixed_crop(src, x0, y0, cw, ch, None)
        return src, label

    def _crop_satisfies(self, label, x1, y1, x2, y2, width, height):
        """The crop window (normalized corners) must cover >
        min_object_covered of at least one non-degenerate object."""
        corners = label[:, 1:]
        pixel_areas = _corner_areas(corners) * width * height
        live = pixel_areas > 2
        if not live.any():
            return False
        kept = _intersect_window(corners[live], x1, y1, x2, y2)
        cover = _corner_areas(kept) / (_corner_areas(corners[live]) + 1e-12)
        cover = cover[cover > 0]
        return cover.size > 0 and float(cover.min()) > self.min_object_covered

    def _relabel(self, label, x0, y0, cw, ch, height, width):
        """Express boxes in crop coordinates; drop ejected objects.
        Returns None when no object survives."""
        wx, wy = x0 / width, y0 / height
        sx, sy = cw / width, ch / height
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - wx) / sx
        out[:, (2, 4)] = (out[:, (2, 4)] - wy) / sy
        out[:, 1:5] = _np.clip(out[:, 1:5], 0.0, 1.0)
        keep_frac = (_corner_areas(out[:, 1:]) * sx * sy
                     / (_corner_areas(label[:, 1:]) + 1e-12))
        alive = ((out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
                 & (keep_frac > self.min_eject_coverage))
        if not alive.any():
            return None
        return out[alive]

    def _propose(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        full = float(height * width)
        for _ in range(self.max_attempts):
            area = _rand.uniform(*self.area_range) * full
            ratio = _rand.uniform(*self.aspect_ratio_range)
            cw = int(round((area * ratio) ** 0.5))
            ch = int(round((area / ratio) ** 0.5))
            if cw < 1 or ch < 1 or cw > width or ch > height or cw * ch < 2:
                continue
            x0 = _rand.randint(0, width - cw)
            y0 = _rand.randint(0, height - ch)
            if not self._crop_satisfies(label, x0 / width, y0 / height,
                                        (x0 + cw) / width, (y0 + ch) / height,
                                        width, height):
                continue
            new_label = self._relabel(label, x0, y0, cw, ch, height, width)
            if new_label is not None:
                return x0, y0, cw, ch, new_label
        return None


class DetRandomPadAug(DetAugmenter):
    """Random expansion: paste the image at a random offset on a larger
    canvas filled with pad_val; boxes shrink accordingly (reference:
    detection.py DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (list, tuple)):
            pad_val = (pad_val,)
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0
                        and area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])
        if not self.enabled:
            logging.warning("DetRandomPadAug disabled: invalid ranges %s %s",
                            area_range, aspect_ratio_range)

    def __call__(self, src, label):
        h, w = int(src.shape[0]), int(src.shape[1])
        found = self._propose(label, h, w)
        if found is not None:
            x0, y0, cw, ch, label = found
            arr = _to_host(src)
            fill = _np.asarray(self.pad_val, dtype=arr.dtype)
            canvas = _np.empty((ch, cw, arr.shape[2]), dtype=arr.dtype)
            canvas[:] = fill
            canvas[y0:y0 + h, x0:x0 + w] = arr
            src = _like(canvas, src)
        return src, label

    def _relabel(self, label, x0, y0, cw, ch, height, width):
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] * width + x0) / cw
        out[:, (2, 4)] = (out[:, (2, 4)] * height + y0) / ch
        return out

    def _propose(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        full = float(height * width)
        lo = max(1.0, self.area_range[0])
        for _ in range(self.max_attempts):
            area = _rand.uniform(lo, self.area_range[1]) * full
            ratio = _rand.uniform(*self.aspect_ratio_range)
            cw = int(round((area * ratio) ** 0.5))
            ch = int(round((area / ratio) ** 0.5))
            # the canvas must strictly contain the image, with enough
            # margin for the pad to matter
            if cw - width < 2 or ch - height < 2:
                continue
            x0 = _rand.randint(0, cw - width)
            y0 = _rand.randint(0, ch - height)
            return x0, y0, cw, ch, self._relabel(label, x0, y0, cw, ch,
                                                 height, width)
        return None


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """One DetRandomCropAug per parameter combination, wrapped in a
    DetRandomSelectAug (reference: detection.py
    CreateMultiRandCropAugmenter).  Scalar parameters broadcast against
    list-valued ones; all lists must share one length."""
    params = [min_object_covered, aspect_ratio_range, area_range,
              min_eject_coverage, max_attempts]
    cols = [p if isinstance(p, list) else [p] for p in params]
    n = max(len(c) for c in cols)
    for i, c in enumerate(cols):
        if len(c) != n:
            if len(c) != 1:
                raise ValueError("parameter lists must have equal length")
            cols[i] = c * n
    augs = [DetRandomCropAug(min_object_covered=moc, aspect_ratio_range=arr,
                             area_range=ar, min_eject_coverage=mec,
                             max_attempts=ma)
            for moc, arr, ar, mec, ma in zip(*cols)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter stack (reference: detection.py:482).

    Ordering matches the reference: resize -> random crop -> mirror ->
    random pad -> force resize to data_shape -> cast -> photometric
    jitter -> normalize.  Geometry before the force-resize keeps the pad
    cheap; photometrics after it run on the small image.
    """
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range, area_range,
            min_eject_coverage, max_attempts, skip_prob=(1 - rand_crop)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range, (1.0, area_range[1]),
                             max_attempts, pad_val)],
            skip_prob=1 - rand_pad))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


# ------------------------------------------------------ iterator


class ImageDetIter(ImageIter):
    """Detection record/list iterator (reference: detection.py:624).

    Reads the same .rec/.lst/imglist sources as ImageIter; labels are
    flat packed-header vectors (see module docstring) parsed into
    per-object rows, augmented jointly with the image, and batched with
    -1 row padding to a fixed (max_objects, obj_width) label shape so
    every batch traces to one static XLA shape.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, shuffle=False,
                 part_index=0, num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="label",
                 preprocess_threads=0, **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         part_index=part_index, num_parts=num_parts,
                         aug_list=[], imglist=imglist, data_name=data_name,
                         label_name=label_name)
        self.auglist = (CreateDetAugmenter(data_shape, **kwargs)
                        if aug_list is None else aug_list)
        # optional thread pool for the per-sample decode+augment chain
        # (reference: iter_image_det_recordio.cc runs it in the worker
        # threads; here PIL's decode/resize release the GIL, so threads
        # overlap the heavy pixel work while record reads stay on the
        # calling thread).  Augment randomness stays reproducible under
        # random.seed/np.random.seed: each sample's seed is drawn on
        # the calling thread and workers draw from per-sample
        # generators (_SampleScopedRandom), so pool scheduling cannot
        # change batch content.
        self._executor = None
        if preprocess_threads and int(preprocess_threads) > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=int(preprocess_threads))
        self.label_shape = self._scan_label_shape()

    # -- label plumbing

    @property
    def provide_label(self):
        return [_io.DataDesc(self._label_name,
                             (self.batch_size,) + self.label_shape)]

    def _parse_label(self, label):
        """Flat packed vector -> (N, obj_width) object rows, dropping
        degenerate boxes (reference: detection.py _parse_label)."""
        if isinstance(label, ndarray.NDArray):
            label = label.asnumpy()
        flat = _np.asarray(label, dtype=_np.float32).ravel()
        if flat.size < 7:
            raise RuntimeError("packed det label too short: %d" % flat.size)
        head, owidth = int(flat[0]), int(flat[1])
        if head < 2 or owidth < 5 or (flat.size - head) % owidth:
            raise RuntimeError(
                "bad det label: header %d obj_width %d size %d"
                % (head, owidth, flat.size))
        rows = flat[head:].reshape(-1, owidth)
        ok = (rows[:, 3] > rows[:, 1]) & (rows[:, 4] > rows[:, 2])
        if not ok.any():
            raise RuntimeError("sample has no valid box")
        return rows[ok]

    def _check_valid_label(self, label):
        if label.ndim != 2 or label.shape[1] < 5:
            raise RuntimeError("label rows must be (N, 5+), got %s"
                               % (label.shape,))
        ok = ((label[:, 0] >= 0) & (label[:, 3] > label[:, 1])
              & (label[:, 4] > label[:, 2]))
        if not ok.any():
            raise RuntimeError("no valid box after augmentation")

    def _scan_label_shape(self):
        """One pass over the epoch to find the max object count — the
        static label shape (reference: _estimate_label_shape).  Samples
        with unparsable labels are skipped, matching next()'s skip
        behavior (the reference crashes here; tolerating stragglers at
        both sites is strictly more useful)."""
        max_objs, width = 0, 5
        self.reset()
        try:
            while True:
                raw, _ = self.next_sample()
                try:
                    rows = self._parse_label(raw)
                except RuntimeError as e:
                    logging.debug("label scan skipping bad sample: %s", e)
                    continue
                max_objs = max(max_objs, rows.shape[0])
                width = rows.shape[1]
        except StopIteration:
            pass
        if max_objs == 0:
            raise RuntimeError("no sample carries a valid detection label")
        self.reset()
        return (max_objs, width)

    def reshape(self, data_shape=None, label_shape=None):
        """Adopt a new data or label shape (reference: ImageDetIter.reshape)."""
        if data_shape is not None:
            if len(data_shape) != 3:
                raise ValueError("data_shape must be (C, H, W)")
            self.data_shape = tuple(data_shape)
            # retarget the force-resize so batches actually come out at
            # the new shape (the reference leaves a stale augmenter here)
            for aug in self.auglist:
                if (isinstance(aug, DetBorrowAug)
                        and isinstance(aug.augmenter, ForceResizeAug)):
                    aug.augmenter.size = (data_shape[2], data_shape[1])
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.label_shape = tuple(label_shape)

    def check_label_shape(self, label_shape):
        if len(label_shape) != 2:
            raise ValueError("label_shape must be (max_objects, obj_width)")
        if label_shape[0] < self.label_shape[0]:
            raise ValueError("cannot shrink max_objects %d -> %d"
                             % (self.label_shape[0], label_shape[0]))
        if label_shape[1] != self.label_shape[1]:
            raise ValueError("obj_width mismatch: %d vs %d"
                             % (self.label_shape[1], label_shape[1]))

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators to a common label shape so train/val
        batches share one static shape (reference: sync_label_shape)."""
        if not isinstance(it, ImageDetIter):
            raise TypeError("sync_label_shape needs another ImageDetIter")
        if self.label_shape[1] != it.label_shape[1]:
            raise ValueError("obj_width mismatch")
        top = max(self.label_shape[0], it.label_shape[0])
        if top > self.label_shape[0]:
            self.reshape(None, (top, self.label_shape[1]))
        if top > it.label_shape[0]:
            it.reshape(None, (top, it.label_shape[1]))
        if verbose:
            logging.info("synced det label shape to %s", (self.label_shape,))
        return it

    # -- batching

    def augmentation_transform(self, data, label):
        for aug in self.auglist:
            data, label = aug(data, label)
        return data, label

    # a bad sample is skipped, not fatal: RuntimeError covers label/
    # augment validation and cv2-backed decode (MXNetError), OSError
    # covers PIL's UnidentifiedImageError on the no-cv2 fallback, and
    # ValueError covers malformed buffers in either decoder
    _SKIP_ERRORS = (RuntimeError, OSError, ValueError)

    def _log_skip(self, err):
        """Per-sample data loss must be OBSERVABLE at default log
        level: warn for the first few skips (and periodically after),
        count all of them (``self.skipped_samples``)."""
        self.skipped_samples = getattr(self, "skipped_samples", 0) + 1
        n = self.skipped_samples
        if n <= 20 or n % 1000 == 0:
            logging.warning("skipping invalid det sample (%d skipped so "
                            "far): %s", n, err)
        else:
            logging.debug("skipping invalid det sample: %s", err)

    def close(self):
        """Release the preprocess thread pool (also runs on GC)."""
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def _load_one(self, raw, buf, seed=None):
        """Per-sample decode + joint augment (thread-pool work item).

        `seed` is a calling-thread draw from the global RNG: when set,
        every augmenter draw for THIS sample comes from generators
        seeded with it, so threaded batches reproduce under
        random.seed/np.random.seed regardless of which pool thread runs
        the sample (ADVICE r4 #3)."""
        from .image import _HostArray, _imdecode_np, _nprand

        if seed is not None:
            _rand.set_sample_rng(random.Random(seed))
            _nprand.set_sample_rng(_np.random.RandomState(seed & 0xffffffff))
        try:
            rows = self._parse_label(raw)
            # the whole per-sample path stays on host numpy; HBM sees
            # one transfer per batch
            img = _imdecode_np(buf).view(_HostArray)
            img, rows = self.augmentation_transform(img, rows)
            self._check_valid_label(rows)
            return img, rows
        finally:
            if seed is not None:
                _rand.set_sample_rng(None)
                _nprand.set_sample_rng(None)

    def _write_slot(self, batch_data, batch_label, i, img, rows):
        from .image import _to_host

        batch_data[i] = _to_host(img).transpose(2, 0, 1)
        n = min(rows.shape[0], self.label_shape[0])
        batch_label[i, :n] = rows[:n]

    def next(self):
        c_h_w = (self.data_shape[0],) + tuple(self.data_shape[1:])
        batch_data = _np.zeros((self.batch_size,) + c_h_w, dtype=_np.float32)
        batch_label = _np.full((self.batch_size,) + self.label_shape, -1.0,
                               dtype=_np.float32)
        i = 0
        exhausted = False
        try:
            while i < self.batch_size and not exhausted:
                if self._executor is None:
                    raw, buf = self.next_sample()  # may StopIteration
                    try:
                        img, rows = self._load_one(raw, buf)
                    except self._SKIP_ERRORS as e:
                        self._log_skip(e)
                        continue
                    self._write_slot(batch_data, batch_label, i, img, rows)
                    i += 1
                    continue
                # threaded: record reads stay on this thread (recordio
                # handles are not thread-safe); decode+augment fans out
                samples = []
                while len(samples) < self.batch_size - i:
                    try:
                        samples.append(self.next_sample())
                    except StopIteration:
                        exhausted = True
                        break
                if not samples:
                    break
                # per-sample seeds drawn HERE, on the calling thread, so
                # the global stream advances deterministically in sample
                # order and thread scheduling cannot change batch content
                futures = [self._executor.submit(self._load_one, raw, buf,
                                                 random.getrandbits(63))
                           for raw, buf in samples]
                for f in futures:
                    try:
                        img, rows = f.result()
                    except self._SKIP_ERRORS as e:
                        self._log_skip(e)
                        continue
                    self._write_slot(batch_data, batch_label, i, img, rows)
                    i += 1
        except StopIteration:
            exhausted = True
        if i == 0:
            raise StopIteration
        return _io.DataBatch(data=[ndarray.array(batch_data)],
                             label=[ndarray.array(batch_label)],
                             pad=self.batch_size - i)
