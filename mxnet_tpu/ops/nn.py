"""Neural-network operators: conv, FC, norm, pooling, activation, softmax.

Reference: src/operator/nn/ (convolution.cc, fully_connected.cc:239-328,
batch_norm.cc, pooling.cc, activation.cc, softmax.cc, dropout.cc,
layer_norm.cc, lrn.cc, upsampling.cc, deconvolution.cc) plus the cuDNN
specializations under src/operator/nn/cudnn/.

TPU-first notes:
- Convolution/FullyConnected lower to ``lax.conv_general_dilated`` /
  ``dot_general`` → the MXU.  Layout stays NCHW at the API (reference
  default); XLA relayouts internally for the TPU (it prefers NHWC and
  does this transformation for free during layout assignment).
- BatchNorm is functional: returns (out, mean, var); running-stat
  updates are performed by the caller (gluon/nn/basic_layers.py) so the
  op stays pure/traceable.  Cross-device sync BN uses lax.pmean when
  running under shard_map (see parallel/).
- Dropout takes an explicit PRNG key input (op purity) — the NDArray
  layer threads keys from mxnet_tpu.random.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import register


def _tup(v, n):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t


def _conv_dn(nd):
    # (lhs, rhs, out) specs for 1/2/3-D NC* layouts
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return lax.conv_dimension_numbers((0,) * (nd + 2), (0,) * (nd + 2), (lhs, rhs, lhs))


@register("Convolution", aliases=("conv",))
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                num_filter=1, num_group=1, no_bias=False, layout=None, cudnn_off=False,
                cudnn_tune=None, workspace=1024, **_):
    """N-D convolution (reference: src/operator/nn/convolution.cc).

    ``layout`` supports the reference's channel-first defaults (NCW/
    NCHW/NCDHW, weight OI+spatial) and the channel-last forms (NWC/
    NHWC/NDHWC) with the reference's OHWI weight convention
    (num_filter, *kernel, in_c/groups — conv-inl.h WeightShape for
    NHWC).  Measured ~+7% on TPU conv trunks (BENCH_NOTES "layout
    experiment").  cudnn_*/workspace attrs are accepted for API parity
    and ignored — XLA picks the TPU algorithm.
    """
    nd = len(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    channel_last = layout is not None and str(layout).endswith("C")
    if channel_last:
        spatial = "DHW"[-nd:]
        spec = ("N" + spatial + "C", "O" + spatial + "I",
                "N" + spatial + "C")
        dn = lax.conv_dimension_numbers((0,) * (nd + 2), (0,) * (nd + 2),
                                        spec)
        bias_shape = (1,) * (nd + 1) + (-1,)
    else:
        dn = _conv_dn(nd)
        bias_shape = (1, -1) + (1,) * nd
    if (channel_last and nd == 2 and _pallas_dw_enabled()
            and all(d == 1 for d in dilate)):
        # backward-filter via the Pallas kernel (pallas_conv.py) where
        # supported; forward and dX keep XLA's lowering bit-for-bit
        out = _nhwc_conv2d_pallas_dw(stride, pad, int(num_group))(
            data, weight)
    else:
        out = lax.conv_general_dilated(
            data, weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=int(num_group),
            preferred_element_type=None,
        )
    if bias is not None and not no_bias:
        out = out + bias.reshape(bias_shape)
    return out


def _pallas_dw_enabled():
    import os

    return os.environ.get("MXTPU_PALLAS_CONV_DW", "0") == "1"


def _pallas_pool_bwd_enabled():
    import os

    return os.environ.get("MXTPU_PALLAS_POOL_BWD", "0") == "1"


@functools.lru_cache(maxsize=None)
def _nhwc_maxpool2d_pallas_bwd(kernel, stride, pad):
    """NHWC 2-D max pool whose input-gradient routes to the Pallas
    gather-style kernel (MXTPU_PALLAS_POOL_BWD=1); forward stays XLA's
    reduce_window."""
    from . import pallas_pool

    window = (1,) + kernel + (1,)
    strides = (1,) + stride + (1,)
    padding = [(0, 0), (pad[0], pad[0]), (pad[1], pad[1]), (0, 0)]

    def raw(x):
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                 padding)

    @jax.custom_vjp
    def pool(x):
        return raw(x)

    def fwd(x):
        return raw(x), x

    def bwd(x, dy):
        if pallas_pool.supported(x.shape, dy.shape, kernel, stride, pad,
                                 ebytes=x.dtype.itemsize):
            return (pallas_pool.maxpool_bwd_nhwc(
                x, dy, kernel, stride, pad).astype(x.dtype),)
        _, vjp = jax.vjp(raw, x)
        return vjp(dy)

    pool.defvjp(fwd, bwd)
    return pool


@functools.lru_cache(maxsize=None)
def _nhwc_conv2d_pallas_dw(stride, pad, groups):
    """NHWC 2-D conv whose weight-gradient routes to the Pallas dW
    kernel (MXTPU_PALLAS_CONV_DW=1).  Forward and data-gradient are
    jax.vjp of the plain lax conv — identical lowerings to the default
    path — so only the measured backward-filter changes."""
    import jax

    from . import pallas_conv

    dn = lax.conv_dimension_numbers((0, 0, 0, 0), (0, 0, 0, 0),
                                    ("NHWC", "OHWI", "NHWC"))

    def raw(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=[(p, p) for p in pad],
            dimension_numbers=dn, feature_group_count=groups)

    @jax.custom_vjp
    def conv(x, w):
        return raw(x, w)

    def fwd(x, w):
        return raw(x, w), (x, w)

    def bwd(res, dy):
        x, w = res
        _, vjp_x = jax.vjp(lambda xx: raw(xx, w), x)
        (dx,) = vjp_x(dy)
        kernel = w.shape[1:3]
        if pallas_conv.supported(x.shape, dy.shape, kernel, stride, pad,
                                 (1, 1), groups,
                                 ebytes=x.dtype.itemsize):
            dw = pallas_conv.conv_dw_nhwc(x, dy, kernel, stride,
                                          pad).astype(w.dtype)
        else:
            _, vjp_w = jax.vjp(lambda ww: raw(x, ww), w)
            (dw,) = vjp_w(dy)
        return dx, dw

    conv.defvjp(fwd, bwd)
    return conv


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                  adj=(), target_shape=(), num_filter=1, num_group=1, no_bias=True,
                  layout=None, **_):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).

    Implemented as the gradient of convolution via lhs-dilation, which XLA
    maps back onto the MXU."""
    nd = len(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    adj = _tup(adj, nd) if adj else (0,) * nd
    kernel = _tup(kernel, nd)
    # weight layout in MXNet deconv: (in_c, out_c/group, *kernel)
    dn = _conv_dn(nd)
    eff_k = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    padding = [(ek - 1 - p, ek - 1 - p + a) for ek, p, a in zip(eff_k, pad, adj)]
    # flip spatial dims + swap in/out channels → standard transposed conv
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if num_group == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        ic, ocg = w.shape[0], w.shape[1]
        w = w.reshape((int(num_group), ic // int(num_group), ocg) + w.shape[2:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((ocg * int(num_group), ic // int(num_group)) + w.shape[3:])
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    )
    if bias is not None:
        # a supplied bias wins over the no_bias flag: the reference's
        # default no_bias=True governs how many inputs it EXPECTS
        # (deconvolution-inl.h), not whether a provided bias is applied
        # — silently dropping a passed bias was a real bug (r3)
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("FullyConnected", aliases=("fc",))
def fully_connected(data, weight, bias=None, num_hidden=1, no_bias=False, flatten=True, **_):
    """reference: src/operator/nn/fully_connected.cc:239-328."""
    if flatten:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


@register("Activation")
def activation(data, act_type="relu", **_):
    """Elementwise activation (reference: src/operator/nn/activation.cc).

    ``act_type``: relu / sigmoid / tanh / softrelu (softplus) /
    softsign — each lowers to the matching jax.nn / jnp primitive."""
    f = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
    }[act_type]
    return f(data)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334, **_):
    """Leaky-ReLU family (reference: src/operator/leaky_relu.cc):
    leaky / prelu (learned ``gamma``) / elu / selu / gelu / rrelu
    (eval-mode mean slope — training rrelu needs the Dropout key path)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        # eval-mode rrelu (mean slope); training rrelu needs RNG — use Dropout-style key path
        return jnp.where(data > 0, data, (lower_bound + upper_bound) / 2.0 * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None, **_):
    """Softmax along ``axis`` (reference: src/operator/nn/softmax.cc)
    with optional ``temperature`` scaling and ``length``-masked
    variable-length rows (masked positions emit exact zeros)."""
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        steps = jnp.arange(x.shape[int(axis)])
        shape = [1] * x.ndim
        shape[int(axis)] = -1
        mask = steps.reshape(shape) < jnp.expand_dims(length, int(axis))
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=int(axis))
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=int(axis))


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, **_):
    """Numerically-stable log(softmax(data)) along ``axis`` with
    optional ``temperature`` (reference: src/operator/nn/softmax.cc)."""
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=int(axis))


@register("softmin")
def softmin(data, axis=-1, **_):
    """softmax(-data): assigns the highest probability to the SMALLEST
    element along ``axis`` (reference: src/operator/nn/softmin.cc)."""
    return jax.nn.softmax(-data, axis=int(axis))


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance", **_):
    """Deprecated-in-reference softmax layer
    (src/operator/nn/softmax_activation.cc): mode='instance' flattens
    each sample, mode='channel' normalizes along axis 1."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@functools.lru_cache(maxsize=None)
def _softmax_output_core(grad_scale, ignore_label, multi_output, use_ignore,
                         normalization, smooth_alpha):
    """Build a custom-vjp softmax-output fn for a static config.

    The backward is the fused (softmax - onehot(label)) cross-entropy
    gradient of the reference (src/operator/softmax_output.cc), ignoring
    the incoming head cotangent — SoftmaxOutput *is* the loss layer.
    """
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(data, label):
        return jax.nn.softmax(data, axis=axis)

    def fwd(data, label):
        out = jax.nn.softmax(data, axis=axis)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        ncls = out.shape[axis]
        lab = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, ncls, dtype=out.dtype, axis=axis)
        if smooth_alpha:
            onehot = (onehot * (1.0 - smooth_alpha)
                      + smooth_alpha / (ncls - 1) * (1.0 - onehot))
        grad = out - onehot
        if use_ignore:
            keep = (lab != int(ignore_label)).astype(out.dtype)
            grad = grad * jnp.expand_dims(keep, axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        elif normalization == "valid":
            if use_ignore:
                scale = scale / jnp.maximum(
                    jnp.sum((lab != int(ignore_label)).astype(out.dtype)), 1.0)
            else:
                scale = scale / float(_np.prod(lab.shape))
        grad = grad * scale
        return (grad.astype(out.dtype), jnp.zeros_like(label))

    f.defvjp(fwd, bwd)
    return f


@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0, multi_output=False,
                   use_ignore=False, preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0, **_):
    """Softmax forward with fused cross-entropy backward
    (reference: src/operator/softmax_output.cc — the Module-API loss layer)."""
    f = _softmax_output_core(float(grad_scale), float(ignore_label),
                             bool(multi_output), bool(use_ignore),
                             str(normalization), float(smooth_alpha))
    return f(data, label)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label, **_):
    """Summed cross-entropy of softmax(data) against integer ``label``
    indices (reference: src/operator/loss_binary_op.cc)."""
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0, **_):
    """Identity forward with fused L2-loss backward ``pred - label``
    (reference: src/operator/regression_output.cc — Module-API head)."""
    return _regression_out(data, label, grad_scale, "linear")


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0, **_):
    """Identity forward with fused L1-loss backward ``sign(pred -
    label)`` (reference: src/operator/regression_output.cc)."""
    return _regression_out(data, label, grad_scale, "mae")


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0, **_):
    """sigmoid(data) forward with the fused cross-entropy backward
    ``pred - label`` (reference: src/operator/regression_output.cc)."""
    return _regression_out(data, label, grad_scale, "logistic")


@functools.lru_cache(maxsize=None)
def _regression_core(grad_scale, kind):
    @jax.custom_vjp
    def f(data, label):
        return jax.nn.sigmoid(data) if kind == "logistic" else data

    def fwd(data, label):
        out = jax.nn.sigmoid(data) if kind == "logistic" else data
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        lab = label.reshape(out.shape)
        num = out.shape[1] if out.ndim > 1 else 1
        if kind == "mae":
            grad = jnp.sign(out - lab)
        else:  # linear & logistic share (pred - label)
            grad = out - lab
        grad = grad * (grad_scale / num)
        # label cotangent must keep the primal label's shape
        return (grad.astype(out.dtype), jnp.zeros_like(label))

    f.defvjp(fwd, bwd)
    return f


def _regression_out(data, label, grad_scale, kind):
    return _regression_core(float(grad_scale), kind)(data, label)


# ---------------------------------------------------------------- norm layers


def _bn_nout(attrs):
    return 3 if attrs.get("output_mean_var") else 1


@register("BatchNorm", num_outputs=_bn_nout)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1,
               cudnn_off=False, axis_name=None, **_):
    """Functional BatchNorm (reference: src/operator/nn/batch_norm.cc).

    Returns out, or (out, batch_mean, batch_var) when ``output_mean_var``.
    The Gluon layer / executor updates moving stats outside (keeps the op
    pure → traceable); when ``use_global_stats`` (inference) the moving
    stats are used directly.
    """
    ax = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    # axis_name: cross-device statistics under EXPLICIT parallelism
    # (shard_map/pmap) — the SyncBatchNorm contract (reference:
    # contrib/sync_batch_norm.cc).  Under GSPMD jit a batch-sharded input
    # already reduces globally without it.
    if use_global_stats:
        mean, var = moving_mean, moving_var
    elif data.dtype in (jnp.bfloat16, jnp.float16):
        # single-pass statistics: E[x] and E[x²] reduce in ONE fused HBM
        # sweep (two-pass (x-mean)² doubled the bandwidth of every BN —
        # the forward is HBM-bound).  fp32 accumulation gives ~2^16 more
        # mantissa than the bf16 inputs, so E[x²]-E[x]² cancellation is
        # bounded by the input's own precision; for fp32 inputs the
        # two-pass form below stays (cancellation would exceed it).
        xf = data.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red)
        meansq = jnp.mean(jnp.square(xf), axis=red)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
            meansq = lax.pmean(meansq, axis_name)
        var = jnp.maximum(meansq - jnp.square(mean), 0.0)
        mean = mean.astype(data.dtype)
        var = var.astype(data.dtype)
    else:
        mean = jnp.mean(data, axis=red)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
        var = jnp.mean(jnp.square(data - _expand(mean, ax, data.ndim)),
                       axis=red)
        if axis_name:
            var = lax.pmean(var, axis_name)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    # scale/shift computed in fp32 (gamma/beta stay fp32 under mixed
    # precision) then applied in the DATA dtype so bf16 activations do
    # not get promoted back to fp32 downstream
    scale = (g.astype(jnp.float32) * inv).astype(data.dtype)
    shift = beta.astype(data.dtype)
    out = (data - _expand(mean.astype(data.dtype), ax, data.ndim)) * \
        _expand(scale, ax, data.ndim) + _expand(shift, ax, data.ndim)
    if output_mean_var:
        return out, mean, var
    return out


def _expand(v, axis, ndim):
    shape = [1] * ndim
    shape[axis] = -1
    return v.reshape(shape)


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False, **_):
    """Layer normalization over ``axis`` with learned ``gamma``/``beta``
    (reference: src/operator/nn/layer_norm.cc)."""
    ax = int(axis)
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=ax, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3, **_):
    """Instance normalization: per-sample, per-channel statistics over
    the spatial axes (reference: src/operator/instance_norm.cc)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance", **_):
    """Scale entries to unit L2 norm per instance/channel/spatial
    position (reference: src/operator/l2_normalization.cc)."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
    elif mode == "channel":
        red = (1,)
    else:  # spatial
        red = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / norm


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **_):
    """Local response norm across channels (reference: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = int(nsize) // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sq = jnp.pad(sq, pad)
    window = sum(
        lax.slice_in_dim(sq, i, i + data.shape[1], axis=1) for i in range(int(nsize))
    )
    return data / jnp.power(knorm + alpha / nsize * window, beta)


# ---------------------------------------------------------------- pooling


@register("Pooling")
def pooling(data, kernel=(), pool_type="max", stride=(), pad=(), global_pool=False,
            pooling_convention="valid", count_include_pad=True, cudnn_off=False,
            p_value=2, layout=None, **_):
    """reference: src/operator/nn/pooling.cc — max/avg/sum/lp pooling,
    'valid' (floor) vs 'full' (ceil) conventions, global pooling."""
    nd = data.ndim - 2
    channel_last = layout is not None and str(layout).endswith("C")
    spatial0 = 1 if channel_last else 2  # first spatial axis
    if global_pool:
        kernel = data.shape[spatial0:spatial0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride else (1,) * nd
    pad = _tup(pad, nd) if pad else (0,) * nd

    spatial_padding = []
    for i in range(nd):
        lo = hi = pad[i]
        if pooling_convention == "full":
            # ceil convention: possibly extra padding on the high side
            size = data.shape[spatial0 + i]
            out_sz = -(-(size + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - size - pad[i]
            hi = max(needed, pad[i])
        spatial_padding.append((lo, hi))
    if channel_last:
        padding = [(0, 0)] + spatial_padding + [(0, 0)]
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        padding = [(0, 0), (0, 0)] + spatial_padding
        window = (1, 1) + kernel
        strides = (1, 1) + stride

    if pool_type == "max":
        if (channel_last and nd == 2 and not global_pool
                and _pallas_pool_bwd_enabled()
                and all(lo == hi for lo, hi in spatial_padding)):
            # backward via the Pallas gather-style kernel
            # (pallas_pool.py) where supported; forward keeps XLA's
            # reduce_window bit-for-bit
            return _nhwc_maxpool2d_pallas_bwd(
                kernel, stride,
                tuple(lo for lo, _hi in spatial_padding))(data)
        init = -jnp.inf
        out = lax.reduce_window(data, init, lax.max, window, strides, padding)
        return out
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad and pooling_convention != "full":
            denom = float(_np.prod(kernel))
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        # a ceil-convention window can land entirely in padding; its
        # count is 0 and 0/0 would poison the batch with NaN — emit 0
        return summed / jnp.maximum(counts, 1.0)
    if pool_type == "lp":
        p = float(p_value)
        powed = lax.reduce_window(jnp.power(jnp.abs(data), p), 0.0, lax.add,
                                  window, strides, padding)
        return jnp.power(powed, 1.0 / p)
    raise ValueError("unknown pool_type %r" % pool_type)


@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **_):
    """reference: src/operator/roi_pooling.cc — fixed-size output so it
    stays jittable (static shapes)."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    H, W = data.shape[2], data.shape[3]

    def pool_one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[bidx]  # (C, H, W)

        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(i, j):
            hstart = y1 + (i * rh) // ph
            hend = y1 + ((i + 1) * rh + ph - 1) // ph
            wstart = x1 + (j * rw) // pw
            wend = x1 + ((j + 1) * rw + pw - 1) // pw
            m = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                 & (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(m[None], img, -jnp.inf)
            v = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        cells = jnp.stack([jnp.stack([cell(i, j) for j in range(pw)], -1)
                           for i in range(ph)], -2)  # (C, ph, pw)
        return cells

    return jax.vmap(pool_one)(rois)


# ---------------------------------------------------------------- dropout


@register("Dropout")
def dropout(key, data, p=0.5, mode="training", axes=(), cudnn_off=False, **_):
    """reference: src/operator/nn/dropout.cc.  ``key`` is an explicit PRNG
    key threaded by the NDArray layer (mxnet_tpu/random.py) so the op is
    pure; in 'always' mode or outside autograd training scope the caller
    passes key=None → identity."""
    if key is None or p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(data.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask


# ---------------------------------------------------------------- resize/upsample


@register("UpSampling")
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=512, **_):
    """Spatial upsampling (reference: src/operator/nn/upsampling.cc):
    'nearest' repeats pixels (multi-input concat supported), 'bilinear'
    uses jax.image.resize in place of the reference's deconv kernel."""
    data = args[0]
    s = int(scale)
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, s, axis=2), s, axis=3)
        if len(args) > 1 and multi_input_mode == "concat":
            outs = [out]
            for a in args[1:]:
                ss = data.shape[2] * s // a.shape[2]
                outs.append(jnp.repeat(jnp.repeat(a, ss, axis=2), ss, axis=3))
            out = jnp.concatenate(outs, axis=1)
        return out
    # bilinear upsampling uses a deconv in the reference; use jax.image
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * s, w * s), method="bilinear")


@register("BilinearSampler")
def bilinear_sampler(data, grid, **_):
    """reference: src/operator/bilinear_sampler.cc (STN sampler)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(img, yy, xx):
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return img[:, yy, xx]

    def sample_one(img, y0_, x0_, wy_, wx_):
        v00 = gather(img, y0_, x0_)
        v01 = gather(img, y0_, x0_ + 1)
        v10 = gather(img, y0_ + 1, x0_)
        v11 = gather(img, y0_ + 1, x0_ + 1)
        return (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)

    return jax.vmap(sample_one)(data, y0, x0, wy, wx)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0), **_):
    """Sampling-grid generation for the spatial transformer (reference:
    src/operator/grid_generator.cc): 'affine' expands 2x3 thetas onto a
    normalized (h, w) mesh, 'warp' converts a flow field to grid
    coordinates."""
    h, w = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        theta = data.reshape((-1, 2, 3))
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)  # (3, h*w)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (n, 2, h*w)
        return out.reshape((-1, 2, h, w))
    # warp type: data is (n, 2, h, w) flow
    n = data.shape[0]
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    fx = (data[:, 0] + gx) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
    fy = (data[:, 1] + gy) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
    return jnp.stack([fx, fy], axis=1)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0), transform_type="affine",
                        sampler_type="bilinear", **_):
    """Spatial transformer network head (reference:
    src/operator/spatial_transformer.cc): affine grid from ``loc``
    thetas + bilinear sampling of ``data``."""
    grid = grid_generator(loc, transform_type="affine", target_shape=target_shape)
    return bilinear_sampler(data, grid)


@register("CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="first", **_):
    """CTC loss (reference: src/operator/contrib/ctc_loss.cc, 3rdparty/ctc_include).

    data: (seq, batch, alphabet) activations (pre-softmax).
    Uses a lax.scan forward algorithm in log space.
    """
    # The reference op contracts its input list by the use_* flags
    # (ctc_loss.cc ListArguments): when only label_lengths is in use, it
    # is the THIRD input.  Positional callers (gluon CTCLoss passes
    # pred_lengths=None) therefore land it in the data_lengths slot.
    if use_label_lengths and not use_data_lengths and label_lengths is None:
        label_lengths, data_lengths = data_lengths, None
    seq_len, batch, alphabet = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else alphabet - 1
    lab = label.astype(jnp.int32)
    if blank_label == "last":
        pass  # labels already 0-based
    max_lab = lab.shape[1]
    if label_lengths is not None and use_label_lengths:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        # reference: 0 (or -1) padding marks end when blank is 'first'
        valid = (lab > 0) if blank == 0 else (lab >= 0)
        lab_len = jnp.sum(valid.astype(jnp.int32), axis=1)
    if data_lengths is not None and use_data_lengths:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((batch,), seq_len, dtype=jnp.int32)

    # extended label sequence with blanks: length 2L+1
    ext_len = 2 * max_lab + 1
    pos = jnp.arange(ext_len)
    ext = jnp.where(pos % 2 == 0, blank, lab[:, jnp.minimum(pos // 2, max_lab - 1)])
    neg_inf = jnp.asarray(-1e30, dtype=logp.dtype)

    alpha0 = jnp.full((batch, ext_len), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_lab = ext[:, 1]
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lab_len > 0, jnp.take_along_axis(logp[0], first_lab[:, None], 1)[:, 0], neg_inf))

    def step(alpha, t):
        lp = logp[t]  # (batch, alphabet)
        emit = jnp.take_along_axis(lp, ext, axis=1)  # (batch, ext_len)
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((batch, 1), neg_inf), alpha[:, :-1]], 1)
        a_shift2 = jnp.concatenate([jnp.full((batch, 2), neg_inf), alpha[:, :-2]], 1)
        same = (ext == jnp.concatenate([jnp.full((batch, 2), -1, dtype=jnp.int32),
                                        ext[:, :-2]], 1))
        is_blank = ext == blank
        allow2 = ~(is_blank | same)
        cand = jnp.where(allow2, jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2),
                         jnp.logaddexp(a_prev, a_shift1))
        new_alpha = cand + emit
        # freeze past data length
        active = (t < dat_len)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, None

    alphaT, _unused = lax.scan(step, alpha0, jnp.arange(1, seq_len))
    end1 = 2 * lab_len
    end2 = 2 * lab_len - 1
    p1 = jnp.take_along_axis(alphaT, end1[:, None], 1)[:, 0]
    p2 = jnp.where(lab_len > 0,
                   jnp.take_along_axis(alphaT, jnp.maximum(end2, 0)[:, None], 1)[:, 0],
                   neg_inf)
    return -jnp.logaddexp(p1, p2)
