"""Long-tail operators closing the registry diff with the reference:
histogram/ravel/split_v2 (tensor), SVMOutput, image ops, fft/count_sketch,
RCNN family (Proposal, PSROIPooling, DeformableConvolution), Correlation,
aggregated multi-tensor SGD, group-adagrad.

Reference files cited per op.  TPU-native stance as elsewhere: static
shapes, masked fixed-capacity formulations for data-dependent outputs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import OP_INPUT_NAMES, register

# ---------------------------------------------------------------- tensor


@register("histogram", aliases=("_histogram",), num_outputs=2)
def histogram(data, bins=None, bin_cnt=10, range=None, **_):
    """reference: src/operator/tensor/histogram.cc — returns
    (counts, bin_edges); bins may be an explicit edge tensor."""
    x = data.ravel().astype(jnp.float32)
    if bins is not None and (hasattr(bins, "__len__") or
                             getattr(bins, "ndim", 0) > 0):
        # explicit (possibly non-uniform) edges: bin by searchsorted
        # (attr canonicalization may deliver them as a tuple)
        edges = jnp.asarray(bins, jnp.float32)
        cnt = edges.shape[0] - 1
    else:
        cnt = int(bin_cnt)
        lo, hi = (range if range else
                  (jnp.min(x), jnp.max(x)))
        edges = jnp.linspace(lo, hi, cnt + 1)
    idx = jnp.clip(jnp.searchsorted(edges, x, side="right") - 1, 0, cnt - 1)
    in_range = (x >= edges[0]) & (x <= edges[-1])
    # int32 counts: jax x64 is off framework-wide (the reference emits
    # int64; values match, dtype differs)
    counts = jnp.zeros(cnt, jnp.int32).at[idx].add(
        in_range.astype(jnp.int32))
    return counts, edges


@register("ravel_multi_index", aliases=("_ravel_multi_index",))
def ravel_multi_index(data, shape=(), **_):
    """reference: tensor/ravel.cc — data (N, M) of N-d indices -> (M,)."""
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= int(s)
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return (data * strides[:, None]).sum(axis=0)


@register("unravel_index", aliases=("_unravel_index",))
def unravel_index(data, shape=(), **_):
    """Convert flat indices into a stacked row of coordinate arrays
    for ``shape`` (row 0 = outermost axis), keeping the input dtype
    (reference: tensor/ravel.cc unravel_index)."""
    out = []
    rem = data.astype(jnp.int64)
    acc = 1
    for s in reversed(shape):
        out.append(rem % int(s))
        rem = rem // int(s)
    return jnp.stack(list(reversed(out)), axis=0).astype(data.dtype)


def _split_v2_nout(attrs):
    if attrs.get("sections", 0):
        return int(attrs["sections"])
    return len(tuple(attrs.get("indices", ()))) + 1


@register("split_v2", aliases=("_split_v2",), num_outputs=_split_v2_nout)
def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0, **_):
    """reference: tensor/matrix_op.cc split_v2 — split by sections or at
    explicit indices."""
    ax = int(axis)
    if sections:
        parts = jnp.split(data, int(sections), axis=ax)
    else:
        parts = jnp.split(data, [int(i) for i in indices], axis=ax)
    if squeeze_axis:
        parts = [p.squeeze(ax) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False, **_):
    """reference: src/operator/svm_output.cc — forward is identity; the
    hinge(-squared) gradient flows in backward."""
    margin = float(margin)
    reg = float(regularization_coefficient)
    use_linear = bool(use_linear)

    @jax.custom_vjp
    def f(x, y):
        return x

    def fwd(x, y):
        return x, (x, y)

    def bwd(res, g):
        x, y = res
        n = x.shape[1]
        onehot = jax.nn.one_hot(y.astype(jnp.int32), n, dtype=x.dtype)
        # margin violation per class vs the true-class score
        true_score = jnp.sum(x * onehot, axis=1, keepdims=True)
        viol = (margin - (true_score - x)) > 0
        if use_linear:  # L1-SVM: +-1 gradients
            gx = jnp.where(viol, 1.0, 0.0) * (1 - onehot)
            gx = gx - onehot * gx.sum(axis=1, keepdims=True)
        else:  # L2-SVM
            slack = jnp.maximum(margin - (true_score - x), 0.0) * (1 - onehot)
            gx = 2.0 * slack
            gx = gx - onehot * gx.sum(axis=1, keepdims=True)
        return (reg * gx * g, jnp.zeros_like(y))

    f.defvjp(fwd, bwd)
    return f(data, label)


# ----------------------------------------------------------------- image


@register("image_to_tensor", aliases=("_image_to_tensor",))
def image_to_tensor(data, **_):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference:
    src/operator/image/image_random.cc ToTensor)."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("image_normalize", aliases=("_image_normalize",))
def image_normalize(data, mean=(0.0,), std=(1.0,), **_):
    """CHW float normalize (reference: image_random.cc Normalize)."""
    c = data.shape[-3]
    mean = jnp.asarray(tuple(mean) * c if len(tuple(mean)) == 1 else mean,
                       data.dtype)[:c]
    std = jnp.asarray(tuple(std) * c if len(tuple(std)) == 1 else std,
                      data.dtype)[:c]
    shape = (c,) + (1,) * 2
    return (data - mean.reshape(shape)) / std.reshape(shape)


@register("image_resize", aliases=("_image_resize",))
def image_resize(data, size=(), keep_ratio=False, interp=1, **_):
    """HWC resize (reference: src/operator/image/resize.cc); bilinear."""
    size = (int(size), int(size)) if isinstance(size, int) else \
        tuple(int(s) for s in size)
    w, h = size if len(size) == 2 else (size[0], size[0])
    method = "nearest" if int(interp) == 0 else "bilinear"
    if data.ndim == 3:
        return jax.image.resize(data, (h, w, data.shape[2]), method=method)
    return jax.image.resize(
        data, (data.shape[0], h, w, data.shape[3]), method=method)


# -------------------------------------------------------------- contrib


@register("_contrib_fft", aliases=("fft",))
def contrib_fft(data, compute_size=128, **_):
    """reference: contrib/fft.cc — complex output interleaved as
    (..., 2n) [re, im, re, im, ...]."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register("_contrib_ifft", aliases=("ifft",))
def contrib_ifft(data, compute_size=128, **_):
    """Inverse FFT over interleaved (re, im) pairs in the last axis,
    returning the real part scaled by n — the inverse of
    ``_contrib_fft``'s packing (reference: contrib/fft.cc IFFT;
    ``compute_size`` is the reference's batching knob, unused here
    since XLA fuses the whole batch)."""
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * n


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=0, **_):
    """Count-sketch projection (reference: contrib/count_sketch.cc):
    out[:, h[j]] += s[j] * data[:, j]."""
    out_dim = int(out_dim)
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    vals = data * ss[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., hh].add(vals)


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          num_outputs=2)
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1, **_):
    """Greedy bipartite matching by score (reference:
    contrib/bounding_box.cc BipartiteMatching): data (..., M, N) scores;
    returns (row->col matches, col->row matches), unmatched = -1."""
    shape = data.shape
    m, n = shape[-2], shape[-1]
    flat = data.reshape((-1, m, n))
    sign = 1.0 if is_ascend else -1.0

    def one(mat):
        def body(_, carry):
            rowm, colm, mat = carry
            best = jnp.argmin(sign * mat)
            i, j = best // n, best % n
            ok = jnp.where(is_ascend, mat[i, j] <= threshold,
                           mat[i, j] >= threshold)
            rowm = jnp.where(ok & (rowm[i] < 0), rowm.at[i].set(j), rowm)
            colm = jnp.where(ok & (colm[j] < 0), colm.at[j].set(i), colm)
            inf = jnp.asarray(jnp.inf * sign, mat.dtype)
            mat = mat.at[i, :].set(inf)
            mat = mat.at[:, j].set(inf)
            return rowm, colm, mat

        k = min(m, n) if topk <= 0 else min(int(topk), min(m, n))
        rowm = jnp.full((m,), -1.0, data.dtype)
        colm = jnp.full((n,), -1.0, data.dtype)
        rowm, colm, _ = lax.fori_loop(0, k, body, (rowm, colm, mat))
        return rowm, colm

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(shape[:-2] + (m,)),
            cols.reshape(shape[:-2] + (n,)))


@register("_contrib_Proposal", aliases=("Proposal", "_contrib_MultiProposal",
                                        "MultiProposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False, **_):
    """RPN proposals (reference: contrib/proposal.cc / multi_proposal.cc):
    anchor grid -> bbox-delta decode -> clip -> NMS -> top-N rois
    (B*post_nms, 5) [batch_idx, x1, y1, x2, y2].  Fixed-capacity: always
    returns post_nms rows per image, low-score rows repeat the best roi."""
    from .contrib import box_nms

    b, num_anchor_x2, h, w = cls_prob.shape
    a = num_anchor_x2 // 2
    stride = float(feature_stride)
    # base anchors centered at origin
    base = []
    for r in ratios:
        for s in scales:
            size = stride * stride
            size_r = size / float(r)
            # reference GenerateAnchors rounds w/h before scaling —
            # pretrained RPNs decode against these exact anchors
            ws = jnp.round(jnp.sqrt(size_r))
            hs = jnp.round(ws * float(r))
            ws, hs = ws * float(s) / stride, hs * float(s) / stride
            base.append([-(ws * stride - stride) / 2,
                         -(hs * stride - stride) / 2,
                         (ws * stride - stride) / 2 + stride - 1,
                         (hs * stride - stride) / 2 + stride - 1])
    base = jnp.asarray(base, cls_prob.dtype)          # (A, 4)
    sx = jnp.arange(w, dtype=cls_prob.dtype) * stride
    sy = jnp.arange(h, dtype=cls_prob.dtype) * stride
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([gx, gy, gx, gy], axis=-1).reshape(-1, 4)  # (HW, 4)
    anchors = (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)

    scores = cls_prob[:, a:, :, :].transpose(0, 2, 3, 1).reshape(b, -1)
    deltas = bbox_pred.transpose(0, 2, 3, 1).reshape(b, -1, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    cx = deltas[..., 0] * aw + acx
    cy = deltas[..., 1] * ah + acy
    pw = jnp.exp(jnp.clip(deltas[..., 2], -10, 10)) * aw
    ph = jnp.exp(jnp.clip(deltas[..., 3], -10, 10)) * ah
    x1 = cx - 0.5 * pw
    y1 = cy - 0.5 * ph
    x2 = cx + 0.5 * pw
    y2 = cy + 0.5 * ph
    imh = im_info[:, 0:1]
    imw = im_info[:, 1:2]
    x1 = jnp.clip(x1, 0, imw - 1)
    x2 = jnp.clip(x2, 0, imw - 1)
    y1 = jnp.clip(y1, 0, imh - 1)
    y2 = jnp.clip(y2, 0, imh - 1)
    # min size scales with the image scale factor (reference proposal.cc:
    # min_size * im_info[2])
    min_size = float(rpn_min_size) * im_info[:, 2:3]
    valid = ((x2 - x1 + 1) >= min_size) & ((y2 - y1 + 1) >= min_size)
    scores = jnp.where(valid, scores, -1.0)

    rows = jnp.stack([scores, x1, y1, x2, y2], axis=-1)  # (B, N, 5)
    pre = min(int(rpn_pre_nms_top_n), rows.shape[1])
    top = jax.vmap(lambda r: r[jnp.argsort(-r[:, 0])[:pre]])(rows)
    kept = box_nms(top, overlap_thresh=float(threshold), coord_start=1,
                   score_index=0, id_index=-1, topk=-1)
    post = int(rpn_post_nms_top_n)

    def finalize(r, bi):
        order = jnp.argsort(-r[:, 0])
        r = r[order][:post]
        best = r[0]
        ok = r[:, 0] > 0
        r = jnp.where(ok[:, None], r, best[None, :])
        idx = jnp.full((post, 1), bi, r.dtype)
        return jnp.concatenate([idx, r[:, 1:5]], axis=-1), r[:, 0:1]

    rois, scr = jax.vmap(finalize)(kept, jnp.arange(b, dtype=cls_prob.dtype))
    rois = rois.reshape(-1, 5)
    if output_score:
        return rois, scr.reshape(-1, 1)
    return rois


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=0.0625, output_dim=1,
                  pooled_size=7, group_size=0, **_):
    """Position-sensitive ROI pooling (reference: contrib/psroi_pooling.cc):
    data (B, output_dim*g*g, H, W), rois (R, 5) -> (R, output_dim, g, g)."""
    g = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    od = int(output_dim)
    bsz, _, hh, ww = data.shape

    def one(roi):
        bi = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale, roi[2] * spatial_scale,
                          roi[3] * spatial_scale, roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        img = data[bi]                                  # (od*g*g, H, W)
        out = jnp.zeros((od, p, p), data.dtype)
        for py in range(p):
            for px in range(p):
                by1 = y1 + rh * py / p
                by2 = y1 + rh * (py + 1) / p
                bx1 = x1 + rw * px / p
                bx2 = x1 + rw * (px + 1) / p
                ymask = (jnp.arange(hh) >= jnp.floor(by1)) & \
                        (jnp.arange(hh) < jnp.ceil(by2))
                xmask = (jnp.arange(ww) >= jnp.floor(bx1)) & \
                        (jnp.arange(ww) < jnp.ceil(bx2))
                mask = ymask[:, None] & xmask[None, :]
                cnt = jnp.maximum(mask.sum(), 1)
                gy = min(py * g // p, g - 1)
                gx = min(px * g // p, g - 1)
                chans = img[(jnp.arange(od) * g + gy) * g + gx]  # (od,H,W)
                pooled = (chans * mask[None]).sum(axis=(1, 2)) / cnt
                out = out.at[:, py, px].set(pooled.astype(data.dtype))
        return out

    return jax.vmap(one)(rois)


@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=1, num_group=1,
                           num_deformable_group=1, no_bias=False, **_):
    """Deformable conv v1 (reference: contrib/deformable_convolution.cc):
    per-output-position learned sampling offsets, bilinear sampling,
    then an ordinary conv contraction.  Implemented as gather+matmul —
    the im2col form, which XLA maps onto the MXU."""
    b, cin, h, w = data.shape
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    ph, pw = int(pad[0]), int(pad[1])
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    ndg = int(num_deformable_group)
    ng = int(num_group)
    assert cin % ndg == 0 and cin % ng == 0

    # sampling grid: base positions + per-deformable-group offsets
    # (B, ndg*2*K, OH, OW), K=kh*kw
    gy = jnp.arange(oh) * sh - ph
    gx = jnp.arange(ow) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = gy[:, None, None, None] + ky[None, None, :, None]  # OH,1,kh,1
    base_x = gx[None, :, None, None] + kx[None, None, None, :]  # 1,OW,1,kw
    base_y = jnp.broadcast_to(base_y, (oh, ow, kh, kw)).astype(data.dtype)
    base_x = jnp.broadcast_to(base_x, (oh, ow, kh, kw)).astype(data.dtype)
    off = offset.reshape(b, ndg, kh * kw, 2, oh, ow)
    oy = off[:, :, :, 0].transpose(0, 1, 3, 4, 2) \
        .reshape(b, ndg, oh, ow, kh, kw)
    ox = off[:, :, :, 1].transpose(0, 1, 3, 4, 2) \
        .reshape(b, ndg, oh, ow, kh, kw)
    sy = base_y[None, None] + oy                    # (B,ndg,OH,OW,kh,kw)
    sx = base_x[None, None] + ox

    def bilinear(img, yy, xx):
        """img (C, H, W); yy/xx (...) -> (C, ...)"""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0

        def at(yi, xi):
            inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            vals = img[:, yc, xc]
            return jnp.where(inside[None], vals, 0.0)

        return (at(y0, x0) * ((1 - wy) * (1 - wx))[None] +
                at(y0, x0 + 1) * ((1 - wy) * wx)[None] +
                at(y0 + 1, x0) * (wy * (1 - wx))[None] +
                at(y0 + 1, x0 + 1) * (wy * wx)[None])

    def one(img, yy, xx):
        # img (C, H, W); yy/xx (ndg, OH, OW, kh, kw): each deformable
        # group samples its channel slice with its own offsets
        parts = []
        cpg = cin // ndg
        for gi in range(ndg):
            parts.append(bilinear(img[gi * cpg:(gi + 1) * cpg],
                                  yy[gi], xx[gi]))
        return jnp.concatenate(parts, axis=0)  # (C, OH, OW, kh, kw)

    cols = jax.vmap(one)(data, sy, sx)        # (B, C, OH, OW, kh, kw)
    nf = int(num_filter)
    if ng == 1:
        cols2 = cols.transpose(0, 2, 3, 1, 4, 5).reshape(
            b * oh * ow, cin * kh * kw)
        wmat = weight.reshape(nf, -1)
        out = (cols2 @ wmat.T).reshape(b, oh, ow, nf)
    else:
        # grouped contraction: each filter group sees its channel slice
        cpg = cin // ng
        fpg = nf // ng
        outs = []
        for gi in range(ng):
            sl = cols[:, gi * cpg:(gi + 1) * cpg]
            sl = sl.transpose(0, 2, 3, 1, 4, 5).reshape(
                b * oh * ow, cpg * kh * kw)
            wmat = weight[gi * fpg:(gi + 1) * fpg].reshape(fpg, -1)
            outs.append((sl @ wmat.T).reshape(b, oh, ow, fpg))
        out = jnp.concatenate(outs, axis=-1)
    out = out.transpose(0, 3, 1, 2)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True, **_):
    """FlowNet correlation layer (reference: src/operator/correlation.cc):
    per-displacement patch products between two feature maps.  Boundary
    handling is by masking invalid overlap to zero (the reference pads by
    pad_size and correlates — masked-roll is the static-shape equivalent,
    so pad_size does not change the output size here); kernel_size>1
    aggregates products over the kernel window; stride1 subsamples the
    output grid."""
    b, c, h, w = data1.shape
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    ks = int(kernel_size)
    disp = list(range(-md, md + 1, s2))
    outs = []
    for dy in disp:
        for dx in disp:
            # channel (dy, dx) correlates data1[y, x] with
            # data2[y+dy, x+dx] (reference: x2 = x1 + displacement), so
            # data2 rolls by the NEGATED displacement
            shifted = jnp.roll(data2, (-dy, -dx), axis=(2, 3))
            ymask = jnp.zeros((h,), bool).at[max(-dy, 0):h + min(-dy, 0)] \
                .set(True)
            xmask = jnp.zeros((w,), bool).at[max(-dx, 0):w + min(-dx, 0)] \
                .set(True)
            mask = (ymask[:, None] & xmask[None, :]).astype(data1.dtype)
            if is_multiply:
                prod = (data1 * shifted).mean(axis=1)
            else:  # reference: positive sum of absolute differences
                prod = jnp.abs(data1 - shifted).mean(axis=1)
            prod = prod * mask[None]
            if ks > 1:  # aggregate + normalize over the kernel window
                prod = lax.reduce_window(
                    prod, 0.0, lax.add, (1, ks, ks), (1, 1, 1),
                    "SAME") / float(ks * ks)
            outs.append(prod)
    out = jnp.stack(outs, axis=1)
    if s1 > 1:
        out = out[:, :, ::s1, ::s1]
    return out


# ------------------------------------------------ aggregated optimizers


def _multi_nout(attrs):
    return int(attrs.get("num_weights", 1))


@register("multi_sgd_update", num_outputs=_multi_nout)
def multi_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1, **_):
    """Aggregated SGD over many (weight, grad) pairs in one launch
    (reference: optimizer_op.cc multi_sgd_update,
    MXNET_OPTIMIZER_AGGREGATION_SIZE) — under jit XLA fuses the loop."""
    n = int(num_weights)
    out = []
    for i in range(n):
        w, g = args[2 * i], args[2 * i + 1]
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        out.append(w - float(lrs[i]) * (g + float(wds[i]) * w))
    return tuple(out) if n > 1 else out[0]


@register("multi_sgd_mom_update", num_outputs=lambda a: 2 * int(
    a.get("num_weights", 1)))
def multi_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1, **_):
    """Aggregated SGD-with-momentum over ``num_weights`` (weight, grad,
    mom) triples in ONE fused kernel, per-tensor lr/wd — the reference's
    multi-tensor apply (optimizer_op.cc multi_sgd_mom_update); outputs
    are the updated weights then the updated momenta."""
    n = int(num_weights)
    new_w, new_m = [], []
    for i in range(n):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nm = momentum * m - float(lrs[i]) * (g + float(wds[i]) * w)
        new_w.append(w + nm)
        new_m.append(nm)
    return tuple(new_w + new_m)


@register("group_adagrad_update", aliases=("_contrib_group_adagrad_update",),
          num_outputs=2)
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5, **_):
    """Row-wise (grouped) AdaGrad (reference: contrib/optimizer_op.cc
    GroupAdagradUpdate): history accumulates the mean squared gradient
    per row."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    gsq = jnp.mean(jnp.square(g), axis=tuple(range(1, g.ndim)))
    new_hist = history + gsq
    scale = lr / (jnp.sqrt(new_hist) + epsilon)
    shape = (-1,) + (1,) * (g.ndim - 1)
    return weight - scale.reshape(shape) * g, new_hist


OP_INPUT_NAMES.update({
    "_contrib_Proposal": ("cls_prob", "bbox_pred", "im_info"),
    "_contrib_PSROIPooling": ("data", "rois"),
    "_contrib_DeformableConvolution": ("data", "offset", "weight", "bias"),
    "Correlation": ("data1", "data2"),
    "group_adagrad_update": ("weight", "grad", "history"),
})


@register("multi_mp_sgd_update", num_outputs=lambda a: 2 * int(
    a.get("num_weights", 1)))
def multi_mp_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1, **_):
    """Multi-tensor multi-precision SGD (reference: optimizer_op.cc
    multi_mp_sgd_update): inputs are (weight, grad, weight32)*N; fp32
    master weights take the update, the low-precision copy mirrors it."""
    n = int(num_weights)
    new_w, new_w32 = [], []
    for i in range(n):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        gf = g.astype(jnp.float32) * rescale_grad
        if clip_gradient >= 0:
            gf = jnp.clip(gf, -clip_gradient, clip_gradient)
        nw32 = w32 - float(lrs[i]) * (gf + float(wds[i]) * w32)
        new_w32.append(nw32)
        new_w.append(nw32.astype(w.dtype))
    return tuple(new_w + new_w32)


@register("multi_mp_sgd_mom_update", num_outputs=lambda a: 3 * int(
    a.get("num_weights", 1)))
def multi_mp_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1, **_):
    """Multi-precision aggregated SGD-momentum over ``num_weights``
    (weight, grad, mom, weight32) quads: the update runs in fp32 master
    weights and the low-precision copy is re-cast per step (reference:
    optimizer_op.cc multi_mp_sgd_mom_update); outputs are updated
    weights, momenta, then master weights."""
    n = int(num_weights)
    new_w, new_m, new_w32 = [], [], []
    for i in range(n):
        w, g, m, w32 = (args[4 * i], args[4 * i + 1], args[4 * i + 2],
                        args[4 * i + 3])
        gf = g.astype(jnp.float32) * rescale_grad
        if clip_gradient >= 0:
            gf = jnp.clip(gf, -clip_gradient, clip_gradient)
        nm = momentum * m - float(lrs[i]) * (gf + float(wds[i]) * w32)
        nw32 = w32 + nm
        new_w.append(nw32.astype(w.dtype))
        new_m.append(nm)
        new_w32.append(nw32)
    return tuple(new_w + new_m + new_w32)


@register("cast_storage_op", aliases=("cast_storage",))
def cast_storage_op(data, stype="default", **_):
    """Storage-type cast op (reference: tensor/cast_storage.cc).  Dense
    jax arrays are the only device representation — the NDArray-level
    sparse wrappers live in ndarray/sparse.py cast_storage — so at op
    level every stype shares the dense buffer: identity."""
    return data


@register("sparse_retain", aliases=("_sparse_retain",))
def sparse_retain_op(data, indices, **_):
    """Row retain (reference: sparse_retain.cc): zero every row of
    `data` whose index is not in `indices` (dense formulation of the
    row_sparse retain; ndarray/sparse.py retain keeps the aux form)."""
    keep = jnp.zeros((data.shape[0],), bool).at[
        indices.astype(jnp.int32)].set(True)
    shape = (-1,) + (1,) * (data.ndim - 1)
    return data * keep.reshape(shape).astype(data.dtype)


@register("_contrib_adamw_update", num_outputs=3)
def contrib_adamw_update(weight, grad, mean, var, rescale_grad, lr=0.001,
                         beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                         eta=1.0, clip_gradient=-1.0, **_):
    """reference: contrib/adamw.cc — rescale_grad is a TENSOR input
    (so loss-scaling can change per step without recompiling)."""
    from .optimizer_ops import adamw_update

    return adamw_update(weight, grad, mean, var, lr=lr, beta1=beta1,
                        beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
                        rescale_grad=jnp.reshape(rescale_grad, ()),
                        clip_gradient=clip_gradient)


@register("_contrib_mp_adamw_update", num_outputs=4)
def contrib_mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad,
                            lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                            wd=0.0, eta=1.0, clip_gradient=-1.0, **_):
    """Multi-precision AdamW: fp32 master weights take the update."""
    from .optimizer_ops import adamw_update

    nw32, nmean, nvar = adamw_update(
        weight32, grad.astype(jnp.float32), mean, var, lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
        rescale_grad=jnp.reshape(rescale_grad, ()).astype(jnp.float32),
        clip_gradient=clip_gradient)
    return nw32.astype(weight.dtype), nmean, nvar, nw32


# v1 / contrib aliases resolving to the modern implementations (only
# where the tensor-input arity actually matches)
from .registry import alias as _alias_op

for _alias, _target in (("BatchNorm_v1", "BatchNorm"),
                        ("Convolution_v1", "Convolution"),
                        ("Pooling_v1", "Pooling"),
                        ("CuDNNBatchNorm", "BatchNorm"),
                        ("_contrib_SparseEmbedding", "Embedding"),
                        ("_contrib_index_copy", "index_copy")):
    _alias_op(_alias, _target)


# ------------------------------------------------- gradient-side ops (r3)

@register("gradientmultiplier", aliases=("_contrib_gradientmultiplier",))
def gradientmultiplier(data, scalar=1.0, **_):
    """Identity forward; backward multiplies the gradient by ``scalar``
    (reference: src/operator/contrib/gradient_multiplier_op.cc — the
    gradient-reversal layer of DANN when scalar < 0)."""
    scalar = float(scalar)

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (scalar * g,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("IdentityAttachKLSparseReg", num_outputs=2)
def identity_attach_kl_sparse_reg(data, moving_avg, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9, _train=None,
                                  **_):
    """Identity forward that attaches a KL sparseness penalty to the
    gradient (reference: src/operator/identity_attach_KL_sparse_reg-inl.h
    — regularizes mean sigmoid activation toward ``sparseness_target``;
    the running mean activation is the aux state, updated once per
    backward there; here the update happens once per *training-mode*
    forward — the same once-per-step cadence under jit, while
    inference forwards leave the aux untouched exactly as the
    reference's Forward does).  Returns (out, new_moving_avg)."""
    from .. import autograd as _autograd

    t = float(sparseness_target)
    pen = float(penalty)
    mom = float(momentum)

    # _train is resolved at the dispatch layer (imperative_invoke) so it
    # participates in the jit cache key; the symbolic path leaves it
    # None and the trace-time scope decides (the executor re-traces per
    # is_train)
    training = _autograd.is_training() if _train is None else _train
    if training:
        new_moving = mom * moving_avg + (1.0 - mom) * data.mean(axis=0)
    else:
        new_moving = moving_avg

    @jax.custom_vjp
    def f(x, avg):
        return x

    def fwd(x, avg):
        return x, avg

    def bwd(avg, g):
        kl = pen * (-t / avg + (1.0 - t) / (1.0 - avg))
        return (g + jnp.broadcast_to(kl, g.shape), jnp.zeros_like(avg))

    f.defvjp(fwd, bwd)
    return f(data, new_moving), new_moving


@register("_square_sum", aliases=("square_sum",))
def square_sum(data, axis=None, keepdims=False, exclude=False, **_):
    """sum(square(x)) as one op (reference:
    src/operator/tensor/square_sum-inl.h — fused so a row_sparse
    input's gradient 2*x*g stays row-sparse; here XLA fuses the dense
    form and the sparse layer routes row_sparse through retained rows)."""
    if axis is not None and not isinstance(axis, (tuple, list)):
        axis = (int(axis),)
    if exclude and axis is not None:
        axis = tuple(i for i in range(data.ndim) if i not in
                     tuple(a % data.ndim for a in axis))
    return jnp.sum(jnp.square(data), axis=None if axis is None
                   else tuple(axis), keepdims=bool(keepdims))
