"""Reduction and broadcast-to operator family.

Reference: src/operator/tensor/broadcast_reduce_op_value.cc and
broadcast_reduce_op_index.cc (sum/mean/prod/max/min/norm/argmax/argmin,
broadcast_to/broadcast_axis).  MXNet axis semantics preserved: ``axis``
may be int, tuple or None; ``keepdims``; ``exclude`` reduces over all
axes *not* listed.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _norm_axis(axis, ndim, exclude=False):
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


_DTYPE_REDUCES = ("sum", "mean", "prod", "nansum", "nanprod")


def _make_reduce(name, jf):
    @register(name, aliases=("%s_axis" % name,))
    def _op(x, axis=None, keepdims=False, exclude=False, dtype=None, **_):
        """Reduce ``x`` over ``axis`` (int, tuple, or None for all
        axes); ``exclude`` reduces over every axis *not* listed,
        ``keepdims`` keeps reduced axes as size 1.  ``dtype`` selects
        the accumulation dtype for sum-like reductions (64-bit
        accumulators stage under ``jax.enable_x64``).  Registered as
        sum/mean/prod/max/min/nansum/nanprod (+ ``*_axis`` aliases)."""
        axes = _norm_axis(axis, x.ndim, exclude)
        if dtype is not None and name in _DTYPE_REDUCES:
            if jnp.dtype(dtype).itemsize == 8:
                # 64-bit accumulation (reference: INT64_TENSOR_SIZE /
                # dtype= on reductions over >2^31-element arrays)
                import jax

                with jax.enable_x64():
                    return jf(x, axis=axes, keepdims=bool(keepdims),
                              dtype=dtype)
            return jf(x, axis=axes, keepdims=bool(keepdims), dtype=dtype)
        return jf(x, axis=axes, keepdims=bool(keepdims))

    return _op


for _name, _jf in [
    ("sum", jnp.sum),
    ("mean", jnp.mean),
    ("prod", jnp.prod),
    ("max", jnp.max),
    ("min", jnp.min),
    ("nansum", jnp.nansum),
    ("nanprod", jnp.nanprod),
]:
    _make_reduce(_name, _jf)


@register("norm")
def norm(x, ord=2, axis=None, keepdims=False, **_):
    """L1/L2 norm of ``x`` over ``axis`` (None reduces all axes);
    only ``ord`` 1 and 2 exist, matching the reference's norm op."""
    axes = None if axis is None else _norm_axis(axis, x.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=axes, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=bool(keepdims)))


def _index_reduce(name, jf):
    @register(name)
    def _op(x, axis=None, keepdims=False, **_):
        """Index of the extremum along ``axis`` (None flattens first),
        returned as float32 indices — the reference's mshadow-legacy
        contract.  Registered as argmax/argmin."""
        if axis is None:
            out = jf(x.reshape(-1), axis=0)
            if keepdims:
                out = out.reshape((1,) * x.ndim)
            return out.astype(jnp.float32)
        out = jf(x, axis=int(axis))
        if keepdims:
            out = jnp.expand_dims(out, int(axis))
        # reference returns float32 indices (mshadow legacy)
        return out.astype(jnp.float32)

    return _op


_index_reduce("argmax", jnp.argmax)
_index_reduce("argmin", jnp.argmin)


@register("argmax_channel")
def argmax_channel(x, **_):
    """Argmax over the channel axis (axis 1) as float32 indices —
    the reference's argmax_channel convenience op."""
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register("broadcast_to")
def broadcast_to(x, shape=None, **_):
    """Broadcast ``x`` to ``shape``; a 0 in the target shape keeps the
    source dim (MXNet convention)."""
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(x, axis=(), size=(), **_):
    """Broadcast the size-1 ``axis`` dims of ``x`` up to the paired
    ``size`` entries (int or tuple forms accepted for both)."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_like")
def broadcast_like(x, y, lhs_axes=None, rhs_axes=None, **_):
    """Broadcast ``x`` to ``y``'s shape; with ``lhs_axes``/``rhs_axes``
    only the paired axes take their size from ``y``."""
    if lhs_axes is None:
        return jnp.broadcast_to(x, y.shape)
    tgt = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = y.shape[ra]
    return jnp.broadcast_to(x, tuple(tgt))


@register("cumsum")
def cumsum(x, axis=None, dtype=None, **_):
    """Cumulative sum along ``axis`` (None flattens first), optionally
    accumulating in ``dtype``."""
    from ..base import np_dtype

    d = np_dtype(dtype) if dtype is not None else None
    if axis is None:
        return jnp.cumsum(x.reshape(-1), dtype=d)
    return jnp.cumsum(x, axis=int(axis), dtype=d)
