"""Fused optimizer update ops.

Reference: src/operator/optimizer_op.cc (sgd_update, sgd_mom_update,
adam_update, rmsprop_update, ftrl_update, signsgd_update, nag_update,
multi-precision variants, and the aggregated multi-tensor updates keyed
by MXNET_OPTIMIZER_AGGREGATION_SIZE).

Each returns the *new* values (weight', states'...) — the Python
optimizer layer writes them back into the NDArrays; under jit the whole
update fuses into one XLA kernel per weight (or one kernel for the whole
aggregated group via optimizer.py's fused multi-tensor path).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _apply_wd_rescale(weight, grad, rescale_grad, clip_gradient, wd):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", traced_attrs=("lr", "wd"))
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False, **_):
    """Plain SGD step ``w' = w - lr * (rescale*clip(g) + wd*w)``
    (reference: src/operator/optimizer_op.cc sgd_update); lr/wd are
    traced so per-step schedules never recompile."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    return weight - lr * g


@register("sgd_mom_update", num_outputs=2, traced_attrs=("lr", "wd"))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False, **_):
    """SGD with momentum: ``m' = momentum*m - lr*g``, ``w' = w + m'``
    (reference: optimizer_op.cc sgd_mom_update); returns (weight',
    mom') fused into one XLA kernel."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2, traced_attrs=("lr", "wd"))
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **_):
    """Nesterov accelerated gradient: momentum update with the
    lookahead correction ``w' = w - lr*(g + momentum*m')`` (reference:
    optimizer_op.cc nag_mom_update); returns (weight', mom')."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3, traced_attrs=("lr", "wd"))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9,
                beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=False, **_):
    """Adam step over first/second-moment state (reference:
    optimizer_op.cc adam_update; bias correction is folded into ``lr``
    by the python Optimizer layer, as in the reference); returns
    (weight', mean', var') as one fused kernel."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("adamw_update", num_outputs=3, traced_attrs=("lr", "wd", "eta"))
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    """reference: src/operator/contrib/adamw.cc (decoupled weight decay)."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    new_w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight)
    return new_w, new_mean, new_var


@register("rmsprop_update", num_outputs=2, traced_attrs=("lr", "wd"))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0, **_):
    """RMSProp (Tieleman & Hinton variant): running squared-gradient
    cache ``n`` scales the step; optional post-update weight clipping
    (reference: optimizer_op.cc rmsprop_update); returns (weight', n')."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_n = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", num_outputs=4, traced_attrs=("lr", "wd"))
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, **_):
    """RMSProp (Graves 2013 centered variant): tracks squared-gradient
    ``n``, gradient mean ``g``, and momentum ``delta``; the variance
    estimate is ``n - g^2`` (reference: optimizer_op.cc
    rmspropalex_update); returns (weight', n', g', delta')."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_n = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1.0 - gamma1) * g + gamma1 * g_state
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g, new_delta


@register("adagrad_update", num_outputs=2, traced_attrs=("lr", "wd"))
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **_):
    """AdaGrad over the dense accumulated-square history (reference:
    optimizer_op.cc adagrad semantics; Duchi et al. 2011); one fused
    kernel on both the eager and whole-step-compiled paths, so the two
    agree to the bit; returns (weight', history')."""
    g = _apply_wd_rescale(weight, grad, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_hist = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_hist) + epsilon), new_hist


@register("adadelta_update", num_outputs=3, traced_attrs=("lr", "wd"))
def adadelta_update(weight, grad, acc_g, acc_delta, lr=0.01, rho=0.9,
                    epsilon=1e-5, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, **_):
    """AdaDelta (Zeiler 2012): the ratio of running RMS accumulators
    sets the step, no lr in the update itself (``lr`` is accepted so
    the shared fused-call protocol fits, and ignored, as in the
    reference); wd decays the weight directly; returns (weight',
    acc_g', acc_delta')."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_acc_g = rho * acc_g + (1.0 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1.0 - rho) * jnp.square(delta)
    return weight - delta - wd * weight, new_acc_g, new_acc_delta


@register("signsgd_update", traced_attrs=("lr", "wd"))
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    """SignSGD: step by the SIGN of the gradient only,
    ``w' = w - lr*(sign(g) + wd*w)`` (reference: optimizer_op.cc
    signsgd_update, Bernstein et al. 2018)."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2, traced_attrs=("lr", "wd"))
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, wd_lh=0.0, **_):
    """Signum: momentum-smoothed SignSGD with optional decoupled decay
    ``wd_lh`` (reference: optimizer_op.cc signum_update); returns
    (weight', mom')."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    new_w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("ftrl_update", num_outputs=3, traced_attrs=("lr", "wd"))
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **_):
    """FTRL-proximal with L1 (``lamda1``) shrinkage over accumulator
    state ``z, n``: weights snap to exact zero inside the L1 ball
    (reference: optimizer_op.cc ftrl_update, McMahan et al. 2013);
    returns (weight', z', n')."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0,
    )
    return new_w, new_z, new_n


@register("ftml_update", num_outputs=3, traced_attrs=("lr", "wd", "t"))
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1, **_):
    """FTML (Follow The Moving Leader, Zheng & Kwok 2017) over state
    ``d, v, z``; the step count ``t`` drives the bias corrections and
    is traced so steps never recompile (reference: optimizer_op.cc
    ftml_update); returns (weight', d', v', z')."""
    g = grad * rescale_grad + wd * weight
    if clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    d_t = (1.0 - jnp.power(beta1, t)) / lr * (
        jnp.sqrt(new_v / (1.0 - jnp.power(beta2, t))) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1.0 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z  # note: 4 outputs (w, d, v, z)


# correct ftml output count
from .registry import get as _get  # noqa: E402

_get("ftml_update").num_outputs = 4


@register("adamax_update", num_outputs=3,
          traced_attrs=("lr", "wd", "t"))
def adamax_update(weight, grad, m, u, lr=0.002, beta1=0.9, beta2=0.999,
                  wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, t=1, **_):
    """Fused Adamax (reference computes this as a python composite,
    optimizer.py Adamax.update; fusing it is the TPU-native choice —
    one XLA kernel instead of ~8 eager dispatches).  The t-dependent
    bias correction is a traced scalar so steps never recompile."""
    g = grad * rescale_grad + wd * weight
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    lr_c = lr / (1.0 - jnp.power(beta1, t))
    new_m = beta1 * m + (1.0 - beta1) * g
    new_u = jnp.maximum(beta2 * u, jnp.abs(g))
    return weight - lr_c * new_m / (new_u + 1e-8), new_m, new_u


@register("nadam_update", num_outputs=3,
          traced_attrs=("lr", "wd", "t", "m_schedule", "momentum_t",
                        "momentum_t_1"))
def nadam_update(weight, grad, m, v, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 t=1, m_schedule=1.0, momentum_t=0.9, momentum_t_1=0.9, **_):
    """Fused Nadam (reference: optimizer.py Nadam.update python
    composite).  ``m_schedule`` is the product *including* this step's
    momentum_t (the host tracks it across steps); the schedule scalars
    are traced so per-step values never recompile."""
    g = grad * rescale_grad + wd * weight
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m_schedule_next = m_schedule * momentum_t_1
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    g_prime = g / (1.0 - m_schedule)
    m_prime = new_m / (1.0 - m_schedule_next)
    v_prime = new_v / (1.0 - jnp.power(beta2, t))
    m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
    new_w = weight - lr * m_bar / (jnp.sqrt(v_prime) + epsilon)
    return new_w, new_m, new_v


@register("mp_sgd_update", num_outputs=2, traced_attrs=("lr", "wd"))
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, **_):
    """Multi-precision SGD: fp32 master weights, low-precision model weights
    (reference: optimizer_op.cc MP_SGD; the fp16→bf16 analog on TPU)."""
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_outputs=3, traced_attrs=("lr", "wd"))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Multi-precision momentum SGD: the update runs on fp32 master
    weights and momentum, then casts back to the model dtype
    (reference: optimizer_op.cc mp_sgd_mom_update); returns (weight',
    mom', weight32')."""
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


# ------------------------------------------------- row_sparse lazy updates
# Reference: optimizer_op.cc SGDUpdateRowSparse / AdamUpdateEx — with a
# row_sparse gradient and lazy_update, ONLY the rows present in the
# gradient are touched (weight rows and optimizer state rows).  TPU-native
# form: XLA scatter on the dense parameter — one fused gather/update/
# scatter per step, bandwidth proportional to the touched rows.

@register("_sparse_sgd_update", traced_attrs=("lr", "wd"))
def sparse_sgd_update(weight, grad_val, grad_idx, lr=0.01, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Lazy row_sparse SGD: only the rows named by ``grad_idx`` are
    gathered, updated, and scattered back — one fused XLA
    gather/update/scatter with bandwidth proportional to the touched
    rows (reference: optimizer_op.cc SGDUpdateRowSparse)."""
    rows = weight[grad_idx]
    g = _apply_wd_rescale(rows, grad_val, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    return weight.at[grad_idx].set(rows - lr * g)


@register("_sparse_sgd_mom_update", num_outputs=2, traced_attrs=("lr", "wd"))
def sparse_sgd_mom_update(weight, grad_val, grad_idx, mom, lr=0.01,
                          momentum=0.0, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0, **_):
    """Lazy row_sparse momentum SGD: weight AND momentum state rows are
    touched only where the gradient has rows (reference:
    optimizer_op.cc sgd_mom_update row_sparse path); returns (weight',
    mom')."""
    rows = weight[grad_idx]
    g = _apply_wd_rescale(rows, grad_val, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mom_rows = momentum * mom[grad_idx] - lr * g
    return (weight.at[grad_idx].set(rows + new_mom_rows),
            mom.at[grad_idx].set(new_mom_rows))


@register("_sparse_adagrad_update", num_outputs=2, traced_attrs=("lr", "wd"))
def sparse_adagrad_update(weight, grad_val, grad_idx, history, lr=0.01,
                          epsilon=1e-7, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0, **_):
    """AdaGrad touching only the gradient's rows (reference:
    src/operator/optimizer_op.cc _sparse_adagrad_update)."""
    rows = weight[grad_idx]
    g = _apply_wd_rescale(rows, grad_val, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_hist_rows = history[grad_idx] + jnp.square(g)
    new_rows = rows - lr * g / (jnp.sqrt(new_hist_rows) + epsilon)
    return (weight.at[grad_idx].set(new_rows),
            history.at[grad_idx].set(new_hist_rows))


@register("_sparse_adam_update", num_outputs=3, traced_attrs=("lr", "wd"))
def sparse_adam_update(weight, grad_val, grad_idx, mean, var,
                       lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Lazy row_sparse Adam: first/second-moment rows decay and update
    only where the gradient has rows (reference: optimizer_op.cc
    AdamUpdateEx lazy path); returns (weight', mean', var')."""
    rows = weight[grad_idx]
    g = _apply_wd_rescale(rows, grad_val, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None, wd)
    new_mean_rows = beta1 * mean[grad_idx] + (1.0 - beta1) * g
    new_var_rows = beta2 * var[grad_idx] + (1.0 - beta2) * jnp.square(g)
    new_rows = rows - lr * new_mean_rows / (jnp.sqrt(new_var_rows) + epsilon)
    return (weight.at[grad_idx].set(new_rows),
            mean.at[grad_idx].set(new_mean_rows),
            var.at[grad_idx].set(new_var_rows))
