"""Pallas TPU kernel for the convolution backward-filter (dW) pass.

Why this exists (BENCH_ROOFLINE.md, r4): in the flagship ResNet-50
step, XLA's backward-filter lowering runs the conv-dW fusion family at
16–44% MXU and 160–500 GB/s — neither compute- nor byte-bound — for
~9 ms of the 48 ms step.  The dW contraction is really a batched
matmul: for every filter tap (r, s),

    dW[r, s, i, o] = sum_{n, y, x} Xp[n, y*sy + r, x*sx + s, i]
                                  * dY[n, y, x, o]

so the TPU-native formulation tiles images through VMEM and issues one
(I × R̂) @ (R̂ × O) MXU contraction per tap per image-block, with the
f32 accumulator resident in VMEM across the sequential image grid
(the flash-attention pattern, attention.py).

Layouts: data NHWC, weight OHWI — the bench model's channel-last
layout (ops/nn.py convolution, layout="NHWC").  Reference analog: the
cuDNN wgrad algos behind src/operator/nn/convolution.cc; here the
kernel IS the algorithm choice.

Two formulations, selected per shape:
* per-tap (kh·kw matmuls of M=I): best when I >= 128 fills the MXU;
* im2col (one matmul of M=kh·kw·I): pays a VMEM concat to raise M for
  narrow layers (I < 128, e.g. ResNet conv2_x I=64 → M=576).

`conv_dw_nhwc` is the public entry; `supported()` reports whether a
shape/config routes to the kernel (else callers fall back to XLA's
lowering).  Integration behind MXTPU_PALLAS_CONV_DW in ops/nn.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas import deferred-safe: CPU-only environments still import
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - pallas always present in-tree
    _HAS_PALLAS = False

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under the ~16 MiB/core


def supported(x_shape, dy_shape, kernel, stride, pad, dilate, groups,
              ebytes=2):
    """True when conv_dw_nhwc handles this configuration (including the
    VMEM fit of a single-image block — callers fall back to XLA's
    lowering otherwise, so an oversized shape must never reach
    pallas_call)."""
    if not _HAS_PALLAS or groups != 1:
        return False
    if any(d != 1 for d in dilate):
        return False
    if len(kernel) != 2:
        return False
    if x_shape[-1] < 8:
        # the stem's I=3 pads the lane dim 128/3x in VMEM; its dW is
        # byte-bound anyway (BENCH_NOTES space-to-depth entry) — XLA
        return False
    n, h, w, _c = dy_shape
    # output spatial must match the conv arithmetic exactly
    hp = x_shape[1] + 2 * pad[0]
    wp = x_shape[2] + 2 * pad[1]
    if (hp - kernel[0]) // stride[0] + 1 != h:
        return False
    if (wp - kernel[1]) // stride[1] + 1 != w:
        return False
    per_image, out_bytes = _sizing(
        (hp, wp, x_shape[-1]), (h, w, dy_shape[-1]), kernel,
        # the auto formulation choice (conv_dw_nhwc) mirrors this
        "im2col" if x_shape[-1] < 128 else "pertap", ebytes)
    return per_image + out_bytes <= _VMEM_BUDGET


def _pad_to(v, m):
    return -(-int(v) // m) * m


def _sizing(xp_hwc, dy_hwc, kernel, formulation, ebytes):
    """(per-image VMEM bytes, accumulator bytes) with TPU vreg padding:
    the minor dim tiles to 128 lanes, the second-minor to 8 sublanes —
    a C=64 operand costs 2x its logical bytes in VMEM."""
    hp, wp, ci = xp_hwc
    oh, ow, co = dy_hwc
    kh, kw = kernel
    per_image = (hp * _pad_to(wp, 8) * _pad_to(ci, 128) +
                 oh * _pad_to(ow, 8) * _pad_to(co, 128)) * ebytes
    if formulation == "im2col":
        per_image += (oh * _pad_to(ow, 8) *
                      _pad_to(kh * kw * ci, 128) * ebytes)
    out_bytes = kh * kw * _pad_to(ci, 8) * _pad_to(co, 128) * 4
    return per_image, out_bytes


def _block_images(n, per_image_bytes, out_bytes):
    """Largest power-of-two image-block fitting the VMEM budget."""
    nb = 1
    while (nb * 2 <= n and n % (nb * 2) == 0 and
           (nb * 2) * per_image_bytes + out_bytes <= _VMEM_BUDGET):
        nb *= 2
    return nb


def _dw_kernel_pertap(x_ref, dy_ref, out_ref, *, kh, kw, sy, sx, oh, ow):
    """One image-block step: kh*kw MXU contractions accumulated into the
    full (kh, kw, I, O) output, which stays VMEM-resident across the
    sequential image grid."""
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    dy = dy_ref[:]
    dyf = dy.reshape(-1, dy.shape[-1])  # (nb*oh*ow, O)
    for r in range(kh):
        for s in range(kw):
            xs = x_ref[:, r:r + sy * oh:sy, s:s + sx * ow:sx, :]
            xsf = xs.reshape(-1, xs.shape[-1])  # (nb*oh*ow, I)
            acc = lax.dot_general(
                xsf, dyf, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (I, O)
            out_ref[r, s] += acc


def _dw_kernel_im2col(x_ref, dy_ref, out_ref, *, kh, kw, sy, sx, oh, ow):
    """One image-block step: a single (kh*kw*I × R̂) @ (R̂ × O)
    contraction — the concat buys MXU rows for narrow-channel layers."""
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    dy = dy_ref[:]
    dyf = dy.reshape(-1, dy.shape[-1])
    taps = []
    for r in range(kh):
        for s in range(kw):
            taps.append(x_ref[:, r:r + sy * oh:sy, s:s + sx * ow:sx, :])
    xcat = jnp.concatenate(taps, axis=-1)          # (nb, oh, ow, kh*kw*I)
    xsf = xcat.reshape(-1, xcat.shape[-1])
    acc = lax.dot_general(xsf, dyf, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    out_ref[:] += acc                              # (kh*kw*I, O)


@functools.partial(jax.jit,
                   static_argnames=("kernel", "stride", "pad", "interpret",
                                    "formulation"))
def conv_dw_nhwc(x, dy, kernel, stride=(1, 1), pad=(0, 0), interpret=False,
                 formulation=None):
    """Backward-filter for NHWC conv with OHWI weights.

    x: (N, H, W, I) forward input; dy: (N, OH, OW, O) output cotangent.
    Returns dW with shape (O, kh, kw, I) in fp32 (the caller casts to
    the weight dtype — matching XLA's fp32 conv accumulation).
    formulation: None (auto), 'pertap', or 'im2col'.
    """
    kh, kw = kernel
    sy, sx = stride
    n, _h, _w, ci = x.shape
    _, oh, ow, co = dy.shape
    if not interpret:
        # CPU/virtual-mesh runs (the test suite) execute the same kernel
        # through the pallas interpreter; Mosaic compiles only on TPU
        interpret = jax.default_backend() != "tpu"
    xp = jnp.pad(x, ((0, 0), (pad[0], pad[0]), (pad[1], pad[1]), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]

    if formulation is None:
        # narrow-channel layers waste MXU rows per tap; buy rows with
        # the im2col concat
        formulation = "im2col" if ci < 128 else "pertap"

    per_image, out_bytes = _sizing((hp, wp, ci), (oh, ow, co), kernel,
                                   formulation, x.dtype.itemsize)
    nb = _block_images(n, per_image, out_bytes)

    if formulation == "im2col":
        kern = functools.partial(_dw_kernel_im2col, kh=kh, kw=kw, sy=sy,
                                 sx=sx, oh=oh, ow=ow)
        out_shape = jax.ShapeDtypeStruct((kh * kw * ci, co), jnp.float32)
        out_spec = pl.BlockSpec((kh * kw * ci, co), lambda g: (0, 0))
    else:
        kern = functools.partial(_dw_kernel_pertap, kh=kh, kw=kw, sy=sy,
                                 sx=sx, oh=oh, ow=ow)
        out_shape = jax.ShapeDtypeStruct((kh, kw, ci, co), jnp.float32)
        out_spec = pl.BlockSpec((kh, kw, ci, co), lambda g: (0, 0, 0, 0))

    dw = pl.pallas_call(
        kern,
        grid=(n // nb,),
        in_specs=[
            pl.BlockSpec((nb, hp, wp, ci), lambda g: (g, 0, 0, 0)),
            pl.BlockSpec((nb, oh, ow, co), lambda g: (g, 0, 0, 0)),
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(xp, dy)

    dw = dw.reshape(kh, kw, ci, co)
    return jnp.transpose(dw, (3, 0, 1, 2))  # OHWI


def conv_dw_xla(x, dy, kernel, stride=(1, 1), pad=(0, 0)):
    """XLA's own backward-filter lowering for the same NHWC/OHWI conv —
    the baseline the Pallas kernel must beat (tools/bench_conv_dw.py)
    and the numerical oracle for its tests."""
    dn = lax.conv_dimension_numbers(
        x.shape, (dy.shape[-1], kernel[0], kernel[1], x.shape[-1]),
        ("NHWC", "OHWI", "NHWC"))

    def fwd(w):
        return lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=[(pad[0], pad[0]), (pad[1], pad[1])],
            dimension_numbers=dn)

    w0 = jnp.zeros((dy.shape[-1], kernel[0], kernel[1], x.shape[-1]),
                   x.dtype)
    _, vjp = jax.vjp(fwd, w0)
    (dw,) = vjp(dy)
    return dw
